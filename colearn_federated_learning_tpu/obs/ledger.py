"""Per-client forensic ledger: in-program client statistics, anomaly
scoring, and attack-attribution reporting (``run.obs.client_ledger``).

PR 2 gave the system run-level observability and PR 1/3 a Byzantine
attack + robust-aggregation stack, but nothing could answer *which
client* did what. This module is the client-level accounting layer
(FedScale's per-client traces / Oort's utility scores are the lineage):

- **In-program round stats** (:func:`client_round_stats`): each round
  program additionally computes a small ``[K, NSTATS]`` block over the
  cohort's wire uploads — update L2 norm, cosine similarity to the
  aggregated delta, clip/EF residual magnitude, post-local-train loss,
  and a robust z-score (median/MAD over the participating cohort) with
  its threshold flag. Computed AFTER the attack transform (forensics
  sees the messages the server sees) and shared verbatim by the
  sharded engine (under jit, on the client-sharded stack), the
  sequential oracle, and the fused scan body — one implementation is
  the parity argument, exactly like ``apply_upload_attack``.
- **The ledger** (:func:`update_ledger`): a device-resident
  ``[num_clients, LEDGER_WIDTH]`` float32 store carried across rounds
  (participation count, cumulative flagged-rounds count, EMA of each
  stat), scattered in-program from the round's stats block — zero
  extra host round-trips, riding the fused ``lax.scan`` carry under
  ``run.fuse_rounds`` exactly like the EF residual store. Poisson pad
  slots (id == num_clients) and dropped clients route to an
  out-of-bounds row and are dropped by the scatter.
- **Reporting** (:func:`clients_report` / :func:`format_clients_report`):
  pure-host aggregation of the driver's periodic ``client_ledger``
  JSONL records into the ``colearn clients <run>`` report — top-k
  anomalous clients, participation histogram, and (when the run had
  ``attack.kind`` set) detection precision/recall of the anomaly flag
  against the ground-truth compromised set the ``attack`` provenance
  event recorded.

The jax-dependent functions import jax lazily so the CLI report path
(like ``obs/summary.py``) stays importable without touching a backend.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# per-round stats block columns ([K, NSTATS], float32; flag is 0/1)
STAT_COLS = ("l2", "cos", "resid", "loss", "z", "flag")
NSTATS = len(STAT_COLS)
# ledger store columns ([num_clients, LEDGER_WIDTH], float32)
LEDGER_COLS = (
    "count", "flagged", "ema_l2", "ema_cos", "ema_resid", "ema_loss",
    "ema_z",
)
LEDGER_WIDTH = len(LEDGER_COLS)


def upload_residual(pre_block, upload_block):
    """Per-client L2 norm of (what the client computed − what it
    shipped) over a ``[width, ...]`` block pair: the clip residual
    (raw Δ vs clipped/compressed upload) on the plain path, exactly
    ``‖eᵢ⁺‖`` under error feedback (pre = Δ+e, upload = C(Δ+e)).
    Shared by the sharded lane (width blocks) and the sequential
    oracle (width-1 blocks) so the stat cannot drift between engines."""
    import jax
    import jax.numpy as jnp

    sq = sum(
        ((a.astype(jnp.float32) - b.astype(jnp.float32))
         .reshape(a.shape[0], -1) ** 2).sum(-1)
        for a, b in zip(jax.tree.leaves(pre_block),
                        jax.tree.leaves(upload_block))
    )
    return jnp.sqrt(sq)


def _masked_median(x, part, m, k):
    """Median of ``x`` over ``part > 0`` rows with static shapes: the
    same sort-with-+inf trick as ``robust_reduce`` — non-participants
    land past every participant, and the order statistics index only
    the first ``m`` rows."""
    import jax.numpy as jnp

    s = jnp.sort(jnp.where(part > 0, x, jnp.inf))
    lo = jnp.clip((m - 1) // 2, 0, k - 1)
    hi = jnp.clip(m // 2, 0, k - 1)
    med = 0.5 * (jnp.take(s, lo) + jnp.take(s, hi))
    return jnp.where(m > 0, med, 0.0)


def _robust_z(x, part, m, k, sign: float):
    """ONE-SIDED robust z-score of each row against the participating
    cohort's median/MAD (1.4826·MAD ≈ σ under normality): the signed
    deviation ``sign·(x − med)``, floored at 0. One-sided because only
    one direction is attack evidence — an above-median upload norm
    (boosting/sign_flip/noise replacement) or a below-median alignment
    (anti-aligned upload); the opposite tails are benign structure
    (small-shard clients ship small deltas, and under krum the selected
    winner's cosine is exactly 1 — neither may flag). The denominator
    carries a relative floor so a near-degenerate cohort (MAD ~ 0, all
    uploads identical) does not turn float noise into flags."""
    import jax.numpy as jnp

    med = _masked_median(x, part, m, k)
    mad = _masked_median(jnp.abs(x - med), part, m, k)
    dev = jnp.maximum(jnp.float32(sign) * (x - med), 0.0)
    return dev / (
        jnp.float32(1.4826) * mad + jnp.float32(1e-6) * jnp.abs(med)
        + jnp.float32(1e-12)
    )


def client_round_stats(uploads, mean_delta, losses, resid, n_ex,
                       zmax: float):
    """One round's ``[K, NSTATS]`` per-client stats block (STAT_COLS
    order), computed from the cohort's WIRE uploads (post clip /
    compression / attack transform — what the server actually
    receives) and the round's aggregated delta:

    - ``l2``   — whole-tree L2 norm of the client's upload.
    - ``cos``  — cosine similarity to the aggregated delta (a sign_flip
      client sits near −1 while the honest cohort clusters positive).
    - ``resid``— the :func:`upload_residual` magnitude (clip/EF).
    - ``loss`` — the client's post-local-train loss.
    - ``z``    — max of the ONE-SIDED robust z-scores (median/MAD over
      the participating cohort) of ``l2`` (above-median only) and
      ``cos`` (below-median only) — the two directions that are attack
      evidence; see :func:`_robust_z` for why the opposite tails are
      excluded.
    - ``flag`` — 1.0 iff ``z > zmax`` and the client participated.

    All math in f32 with one shared implementation across engines; the
    non-participant rows carry whatever the padded computation produced
    (their ``flag`` is forced 0) — :func:`update_ledger` drops them."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(uploads)
    k = leaves[0].shape[0]
    part = (n_ex > 0).astype(jnp.float32)
    m = part.sum().astype(jnp.int32)
    sq = sum(
        (d.astype(jnp.float32).reshape(k, -1) ** 2).sum(-1) for d in leaves
    )
    l2 = jnp.sqrt(sq)
    mleaves = jax.tree.leaves(mean_delta)
    dot = sum(
        (d.astype(jnp.float32).reshape(k, -1)
         @ g.astype(jnp.float32).reshape(-1))
        for d, g in zip(leaves, mleaves)
    )
    gnorm = jnp.sqrt(sum(
        (g.astype(jnp.float32) ** 2).sum() for g in mleaves
    ))
    cos = dot / (l2 * gnorm + jnp.float32(1e-12))
    z = jnp.maximum(
        _robust_z(l2, part, m, k, sign=1.0),   # oversized uploads
        _robust_z(cos, part, m, k, sign=-1.0),  # anti-aligned uploads
    )
    flag = ((z > jnp.float32(zmax)) & (part > 0)).astype(jnp.float32)
    return jnp.stack(
        [l2, cos, resid.astype(jnp.float32), losses.astype(jnp.float32),
         z, flag],
        axis=1,
    )


def update_ledger(ledger, cohort_ids, n_ex, stats, ema: float):
    """Scatter one round's stats block into the ``[rows, LEDGER_WIDTH]``
    ledger: participants' rows get ``count += 1``, ``flagged += flag``,
    and each EMA column moves by ``ema·(x − ema_x)`` (a client's FIRST
    observation seeds the EMA with the value itself). Non-participants
    and poisson pad slots (id == rows) are routed out of bounds, so
    ``take``'s fill and the ``drop``-mode scatter make them exact
    no-ops — the same OOB discipline as the EF store scatter. Cohorts
    sample without replacement, so in-range rows are unique and the
    scatter is well-defined."""
    import jax.numpy as jnp

    rows = ledger.shape[0]
    part = n_ex > 0
    ids = jnp.where(part, cohort_ids.astype(jnp.int32), jnp.int32(rows))
    prev = jnp.take(ledger, ids, axis=0, mode="fill", fill_value=0.0)
    count = prev[:, 0]
    first = (count <= 0)[:, None]
    vals = stats[:, :5]  # l2, cos, resid, loss, z
    emas = prev[:, 2:]
    new_emas = jnp.where(
        first, vals, emas + jnp.float32(ema) * (vals - emas)
    )
    new_rows = jnp.concatenate(
        [(count + 1.0)[:, None], (prev[:, 1] + stats[:, 5])[:, None],
         new_emas],
        axis=1,
    )
    return ledger.at[ids].set(new_rows, mode="drop")


# ---------------------------------------------------------------------------
# paged ledger (run.obs.client_ledger.hot_capacity) — the million-client
# mode: a [hot_capacity, LEDGER_WIDTH] device-resident HOT set scattered
# by slot, cold rows spilled to a host mmap
# ---------------------------------------------------------------------------


class LedgerPager:
    """Hot/cold paging for the per-client ledger.

    The round program is untouched: it still gathers/scatters a
    ``[rows, LEDGER_WIDTH]`` ledger by a ``[K]`` int32 id input — the
    driver simply hands it a ``[hot_capacity, ...]`` array and SLOT ids
    instead of the dense ``[num_clients, ...]`` array and client ids.
    This class owns the host-side slot bookkeeping:

    - ``slot_clients[s]`` — the client resident in slot ``s`` (−1 free);
      ``slot_used[s]`` — the last round that touched it (the LRU key).
      Both ride the checkpoint, so a resumed run's slot assignment
      replays the straight run's exactly (assignment is a pure function
      of the cohort sequence + this state).
    - the COLD store — a ``[num_clients, LEDGER_WIDTH]`` float32
      ``np.memmap`` over an anonymous temp file (unlinked immediately:
      the mapping lives, the directory entry doesn't). Host RSS is
      O(touched pages), never O(num_clients); disk is
      ``num_clients × 28`` bytes.

    Correctness contract (test-pinned): for any cohort that fits the
    hot set, a slot row holds exactly the row the dense ledger would —
    page-in seeds the slot from the client's cold row (zeros if never
    seen), eviction writes the hot row back first — so stats updates,
    reputation trust, and adaptive scoring read/write identical values
    and the MERGED (cold ∪ hot) ledger is bitwise-equal to a dense
    run's. Evictions need the CURRENT hot values, which costs one
    blocking device fetch (``fetch_hot``) — counted in ``page_syncs``;
    page-ins ride an async device scatter and cost nothing.
    """

    def __init__(self, num_clients: int, hot_capacity: int) -> None:
        if not 0 < hot_capacity < num_clients:
            raise ValueError(
                f"hot_capacity must be in (0, num_clients={num_clients}); "
                f"got {hot_capacity}"
            )
        self.num_clients = int(num_clients)
        self.hot_capacity = int(hot_capacity)
        fd, path = tempfile.mkstemp(prefix="colearn_ledger_cold_")
        os.close(fd)
        self.cold = np.memmap(path, dtype=np.float32, mode="w+",
                              shape=(self.num_clients, LEDGER_WIDTH))
        os.unlink(path)  # anonymous: freed with the last mapping
        self.slot_clients = np.full(self.hot_capacity, -1, np.int64)
        self.slot_used = np.full(self.hot_capacity, -1, np.int64)
        self._client_slot: Dict[int, int] = {}
        self.evictions = 0
        self.page_syncs = 0
        # population-health counters (obs/population.py): cohort
        # members already hot-resident at assign time vs page-ins, and
        # the cumulative wall time the blocking eviction write-backs
        # stalled the round loop. Counts are pure functions of the
        # cohort schedule (engine-parity material); sync_ms is wall
        # clock and excluded from the parity pin.
        self.hits = 0
        self.misses = 0
        self.page_ins = 0
        self.sync_ms = 0.0

    # ---- persistence (rides the driver's checkpoint state) -----------

    def load_state(self, slot_clients, slot_used, cold) -> None:
        self.slot_clients[:] = np.asarray(slot_clients, np.int64)
        self.slot_used[:] = np.asarray(slot_used, np.int64)
        self.cold[:] = np.asarray(cold, np.float32)
        self._client_slot = {
            int(c): int(s) for s, c in enumerate(self.slot_clients) if c >= 0
        }

    # ---- paging ------------------------------------------------------

    def write_back(self, hot: np.ndarray) -> None:
        """Mirror every occupied hot row into the cold store (after
        this, ``cold`` IS the merged ledger)."""
        occ = np.flatnonzero(self.slot_clients >= 0)
        if occ.size:
            self.cold[self.slot_clients[occ]] = np.asarray(hot)[occ]

    def lookup(self, ids) -> np.ndarray:
        """Client ids → resident slot ids; pads (id == num_clients) and
        non-resident clients map to ``hot_capacity`` — out of bounds for
        the hot array, so take-fill/scatter-drop make them no-ops
        exactly like the dense path's pad handling."""
        ids = np.asarray(ids, np.int64)
        return np.asarray(
            [self._client_slot.get(int(c), self.hot_capacity) for c in ids],
            np.int32,
        )

    def assign(self, cohort_ids, round_idx: int,
               fetch_hot: Callable[[], np.ndarray],
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Ensure every real client in ``cohort_ids`` is hot-resident.

        Returns ``(slots, new_slots, seed_rows)``: the per-cohort slot
        ids (pads → hot_capacity), plus the slots that were just paged
        in and the cold rows to seed them with (the caller scatters
        those into the device array — async, no sync). Evicting (no
        free slot) first write-backs the CURRENT hot values via
        ``fetch_hot`` — the one blocking sync, counted in
        ``page_syncs``; LRU victims are never members of this cohort.
        """
        ids = np.asarray(cohort_ids, np.int64)
        real = np.unique(ids[(ids >= 0) & (ids < self.num_clients)])
        missing = [int(c) for c in real if int(c) not in self._client_slot]
        self.hits += len(real) - len(missing)
        self.misses += len(missing)
        self.page_ins += len(missing)
        free = np.flatnonzero(self.slot_clients < 0)
        if len(missing) > len(free):
            protected = {
                self._client_slot[int(c)] for c in real
                if int(c) in self._client_slot
            }
            t0 = time.perf_counter()
            hot = np.asarray(fetch_hot())
            self.write_back(hot)
            self.sync_ms += (time.perf_counter() - t0) * 1000.0
            self.page_syncs += 1
            occupied = np.flatnonzero(self.slot_clients >= 0)
            victims = [s for s in occupied if s not in protected]
            # oldest first; slot id breaks ties deterministically
            victims.sort(key=lambda s: (self.slot_used[s], s))
            for s in victims[: len(missing) - len(free)]:
                del self._client_slot[int(self.slot_clients[s])]
                self.slot_clients[s] = -1
                self.slot_used[s] = -1
                self.evictions += 1
            free = np.flatnonzero(self.slot_clients < 0)
        if len(missing) > len(free):
            raise RuntimeError(
                f"paged ledger: cohort needs {len(missing)} page-ins but "
                f"only {len(free)} hot slots can be freed "
                f"(hot_capacity={self.hot_capacity}) — the construction-"
                f"time capacity check should have prevented this"
            )
        new_slots = free[: len(missing)].astype(np.int64)
        for c, s in zip(missing, new_slots):
            self._client_slot[c] = int(s)
            self.slot_clients[s] = c
        seed_rows = np.asarray(self.cold[np.asarray(missing, np.int64)]
                               if missing else
                               np.zeros((0, LEDGER_WIDTH), np.float32))
        for c in real:
            self.slot_used[self._client_slot[int(c)]] = round_idx
        return self.lookup(ids), new_slots.astype(np.int32), seed_rows

    # ---- reporting / snapshots ---------------------------------------

    def active_rows(self, hot: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(client ids, rows) of every client with ≥1 participation, in
        id order, from the merged hot ∪ cold view (write-back included).
        O(touched cold pages) host residency; the returned block is
        O(active clients)."""
        self.write_back(hot)
        active = np.flatnonzero(self.cold[:, 0] > 0)
        return active, np.array(self.cold[active])

    def merged(self, hot: np.ndarray) -> np.ndarray:
        """The dense ``[num_clients, LEDGER_WIDTH]`` merged ledger (a
        fresh array — parity tests and small-N snapshot paths only)."""
        self.write_back(hot)
        return np.array(self.cold)


# ---------------------------------------------------------------------------
# host-side reporting (`colearn clients`) — pure stdlib + the JSONL
# ---------------------------------------------------------------------------


def latest_ledger_record(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    recs = [r for r in records if r.get("event") == "client_ledger"]
    if not recs:
        raise ValueError(
            "no client_ledger records in this run — enable the ledger "
            "with run.obs.client_ledger.enabled=true"
        )
    return recs[-1]


def clients_report(records: List[Dict[str, Any]], top_k: int = 10,
                   min_flag_rate: float = 0.5) -> Dict[str, Any]:
    """Fold a run's JSONL into the per-client forensic report: top-k
    anomalous clients (by cumulative flagged rounds, then EMA z),
    participation histogram, and — when the run carried an ``attack``
    provenance event — detection precision/recall of the anomaly flag
    against the ground-truth compromised set. A client is *detected*
    when it was flagged in at least ``min_flag_rate`` of its
    participations (a one-off flag on an honest client should not count
    as a detection; a persistent attacker is flagged every round)."""
    led = latest_ledger_record(records)
    ids = [int(i) for i in led.get("ids", [])]
    count = [float(c) for c in led.get("count", [])]
    flagged = [float(f) for f in led.get("flagged", [])]
    n = len(ids)
    rate = [flagged[i] / count[i] if count[i] else 0.0 for i in range(n)]
    clients = []
    for i in range(n):
        clients.append({
            "client": ids[i],
            "count": int(count[i]),
            "flagged": int(flagged[i]),
            "flag_rate": round(rate[i], 4),
            **{
                col: round(float(led[col][i]), 6)
                for col in LEDGER_COLS[2:] if col in led
            },
        })
    by_anomaly = sorted(
        clients, key=lambda c: (-c["flagged"], -c.get("ema_z", 0.0),
                                c["client"])
    )
    hist: Dict[int, int] = {}
    for c in count:
        hist[int(c)] = hist.get(int(c), 0) + 1
    report: Dict[str, Any] = {
        "round": int(led.get("round", 0)),
        "tracked_clients": n,
        "total_participations": int(sum(count)),
        "participation_histogram": [
            [k, v] for k, v in sorted(hist.items())
        ],
        "top_anomalous": by_anomaly[:max(0, int(top_k))],
        "min_flag_rate": min_flag_rate,
    }
    attack_ev = next(
        (r for r in records if r.get("event") == "attack"), None
    )
    if attack_ev is not None:
        byz = {int(c) for c in attack_ev.get("compromised", [])}
        detected = {
            c["client"] for c in clients
            if c["count"] and c["flag_rate"] >= min_flag_rate
        }
        seen_byz = byz & set(ids)
        tp = len(detected & byz)
        fp = len(detected - byz)
        fn = len(seen_byz - detected)
        report["attack"] = {
            "kind": attack_ev.get("kind"),
            "n_compromised": len(byz),
            "n_compromised_seen": len(seen_byz),
            "detected": sorted(detected),
            "true_positives": tp,
            "false_positives": fp,
            "false_negatives": fn,
            "precision": round(tp / len(detected), 4) if detected else 0.0,
            # recall over the compromised clients the ledger could have
            # seen (ones never sampled into a cohort are undetectable)
            "recall": round(tp / len(seen_byz), 4) if seen_byz else 0.0,
        }
    return report


DEFAULT_SWEEP_THRESHOLDS = (0.1, 0.25, 0.5, 0.75, 0.9)


def threshold_sweep(records: List[Dict[str, Any]],
                    thresholds=DEFAULT_SWEEP_THRESHOLDS) -> List[Dict[str, Any]]:
    """Detection precision/recall at several ``min-flag-rate`` cutoffs
    from ONE run's JSONL — so an operator can pick the detection
    threshold without re-running training (``colearn clients
    --threshold-sweep``). Requires the run to carry an ``attack``
    provenance event (without ground truth there is nothing to score
    against — raises ValueError with that explanation). Each row:
    ``{threshold, detected, true_positives, false_positives,
    false_negatives, precision, recall}``."""
    if not any(r.get("event") == "attack" for r in records):
        raise ValueError(
            "threshold sweep requires an attack provenance event in the "
            "run log (precision/recall need the ground-truth compromised "
            "set; benign runs have nothing to score against)"
        )
    rows = []
    for t in thresholds:
        rep = clients_report(records, top_k=0, min_flag_rate=float(t))
        atk = rep["attack"]
        rows.append({
            "threshold": float(t),
            "detected": len(atk["detected"]),
            "true_positives": atk["true_positives"],
            "false_positives": atk["false_positives"],
            "false_negatives": atk["false_negatives"],
            "precision": atk["precision"],
            "recall": atk["recall"],
        })
    return rows


def format_threshold_sweep(rows: List[Dict[str, Any]]) -> str:
    """Render the sweep as an aligned text table."""
    lines = [
        f"{'min-flag-rate':>14}{'detected':>10}{'tp':>5}{'fp':>5}"
        f"{'fn':>5}{'precision':>11}{'recall':>8}"
    ]
    for r in rows:
        lines.append(
            f"{r['threshold']:>14.2f}{r['detected']:>10}"
            f"{r['true_positives']:>5}{r['false_positives']:>5}"
            f"{r['false_negatives']:>5}{r['precision']:>11.3f}"
            f"{r['recall']:>8.3f}"
        )
    return "\n".join(lines)


def format_clients_report(report: Dict[str, Any], path: str = "") -> str:
    """Render the clients report as an aligned text table."""
    lines = []
    head = f"run: {path}" if path else "client ledger"
    head += (
        f"  round: {report['round']}"
        f"  clients tracked: {report['tracked_clients']}"
        f"  participations: {report['total_participations']}"
    )
    lines.append(head)
    hist = report.get("participation_histogram") or []
    if hist:
        lines.append(
            "participation (rounds -> clients): "
            + ", ".join(f"{k}x{v}" for k, v in hist)
        )
    top = report.get("top_anomalous") or []
    if top:
        lines.append("")
        lines.append(
            f"{'client':>8}{'rounds':>8}{'flagged':>9}{'rate':>7}"
            f"{'ema_z':>10}{'ema_l2':>11}{'ema_cos':>9}{'ema_loss':>10}"
        )
        for c in top:
            lines.append(
                f"{c['client']:>8}{c['count']:>8}{c['flagged']:>9}"
                f"{c['flag_rate']:>7.2f}{c.get('ema_z', 0.0):>10.2f}"
                f"{c.get('ema_l2', 0.0):>11.4g}"
                f"{c.get('ema_cos', 0.0):>9.3f}"
                f"{c.get('ema_loss', 0.0):>10.4g}"
            )
    else:
        lines.append("no clients tracked yet")
    atk = report.get("attack")
    if atk:
        lines.append("")
        lines.append(
            f"attack: {atk['kind']}  compromised: {atk['n_compromised']} "
            f"({atk['n_compromised_seen']} seen)  detected: "
            f"{len(atk['detected'])}"
        )
        lines.append(
            f"detection precision: {atk['precision']:.3f}  recall: "
            f"{atk['recall']:.3f}  (flag rate >= "
            f"{report['min_flag_rate']})"
        )
    return "\n".join(lines)


def clients_report_path(path: str, top_k: int = 10,
                        min_flag_rate: float = 0.5) -> Dict[str, Any]:
    from colearn_federated_learning_tpu.obs.summary import load_records

    return clients_report(load_records(path), top_k=top_k,
                          min_flag_rate=min_flag_rate)
