"""Federation health observatory: population-scale data-plane telemetry
(``run.obs.population``) and the ``colearn watch`` / ``colearn
population`` CLIs.

PR 9 made every round-loop structure O(cohort); this module is the
observability half that scale story was missing. The structures that
carry a 10⁶-client federation — the streaming score sketch, the ledger
pager, the mmap client store — were nearly blind: run_summary held two
pager totals and nothing else, so a cold-start pager thrash, a sketch
that never covers the attacker population, or a store gather stall were
indistinguishable from "slow". The :class:`PopulationTracker` closes
that gap with one ``population_health`` JSONL record per metrics-flush
window covering four planes:

- **sampler health** — cumulative unique-client coverage via an
  O(1)-memory probabilistic counter (:class:`HLLCounter`, an
  HLL-style register sketch over a fixed splitmix64 hash — seed-pure:
  the same cohort schedule always produces the same estimate),
  the per-window exploration/exploitation draw split (the streaming
  sampler tallies which pool each accepted draw came from), streaming-
  sketch occupancy / refresh age / sketch-vs-universe flag-rate
  coverage, and the cohort staleness distribution (rounds since each
  member's last participation, over a bounded recency map).
- **ledger-pager health** — per-window hit/miss/page-in/eviction/
  page-sync counts and page-sync stall ms, extending the PR 9
  run_summary *totals* into a time series.
- **store I/O** — bytes gathered, gather wall ms, per-shard touch
  counts from ``ShardedRecordArray``, and the union-slab dedup ratio
  under stream placement (rows indexed vs unique rows gathered).
- **participation fairness** — Gini / max-share over a bounded top-k
  participation sketch (:class:`SpaceSavingSketch`), never a dense
  ``[num_clients]`` histogram.

Purity discipline (the wire-counter/roofline contract): every tracked
quantity is a pure function of host-side facts that are identical
across the sharded, sequential, and fused engines (the cohort schedule,
the pager's slot bookkeeping, the slab index tensors), so the
count-based columns of ``population_health`` records are engine-parity
PINNED — only wall-clock fields (every key ends in ``_ms``) may differ.
Every structure is O(cohort) per round or fixed-size (HLL registers,
sketch capacity, recency map), so the records themselves survive the
10⁶-client smoke; tracking never touches the device, the rng streams,
or anything the round program consumes.

The CLI half is pure stdlib (importable without a jax backend, like
``obs/summary.py``): :func:`read_complete_records` tails a metrics
JSONL incrementally — a torn (unterminated or mid-record truncated)
tail line is left for the next poll, never crashes the tailer —
:func:`watch_snapshot` / :func:`format_watch` render the live view
(rounds/sec, loss, health/divergence state, pager hit rate, coverage %,
phase-ms sparklines), and :func:`population_report` /
:func:`format_population_report` are the post-hoc twin.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# the O(1)-memory probabilistic unique-client counter
# ---------------------------------------------------------------------------

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: the fixed, seed-free hash the
    coverage counter buckets client ids with. Fixed constants ⇒ the
    same id always lands in the same register with the same rank, on
    every engine and every run — the counter's seed-purity contract."""
    x = (x.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15)) & _M64
    x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & _M64
    x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & _M64
    return x ^ (x >> np.uint64(31))


def _clz64(x: np.ndarray) -> np.ndarray:
    """Vectorized count-leading-zeros over uint64 (binary search —
    exact, unlike float log2 at 64-bit precision)."""
    x = x.astype(np.uint64)
    zero = x == 0
    clz = np.zeros(x.shape, np.int64)
    for s in (32, 16, 8, 4, 2, 1):
        top = x >> np.uint64(64 - s)
        empty = top == 0
        clz += np.where(empty, s, 0)
        x = np.where(empty, x << np.uint64(s), x)
    return np.where(zero, 64, clz)


class HLLCounter:
    """HyperLogLog-style distinct counter: ``2**bits`` one-byte
    registers (4 KiB at the default 12 bits), ~1.04/√m relative error.
    ``add`` is O(batch); memory never grows with the population —
    exactly the structure that lets "how many of the 10⁶ clients has
    this run ever touched" ride every flush window for free."""

    def __init__(self, bits: int = 12):
        if not 4 <= bits <= 18:
            raise ValueError(f"hll bits must be in [4, 18], got {bits}")
        self.bits = int(bits)
        self.m = 1 << self.bits
        self.registers = np.zeros(self.m, np.uint8)

    def add(self, ids) -> None:
        ids = np.asarray(ids, np.uint64).reshape(-1)
        if ids.size == 0:
            return
        h = _splitmix64(ids)
        bucket = (h >> np.uint64(64 - self.bits)).astype(np.int64)
        w = (h << np.uint64(self.bits)) & _M64
        rho = np.minimum(_clz64(w) + 1, 64 - self.bits + 1).astype(np.uint8)
        np.maximum.at(self.registers, bucket, rho)

    def estimate(self) -> int:
        m = float(self.m)
        if m == 16:
            alpha = 0.673
        elif m == 32:
            alpha = 0.697
        elif m == 64:
            alpha = 0.709
        else:
            alpha = 0.7213 / (1.0 + 1.079 / m)
        raw = alpha * m * m / float(
            np.sum(np.exp2(-self.registers.astype(np.float64)))
        )
        zeros = int(np.count_nonzero(self.registers == 0))
        if raw <= 2.5 * m and zeros:
            # small-range (linear counting) correction — near-exact for
            # populations well under the register count
            raw = m * np.log(m / zeros)
        return int(round(raw))


# ---------------------------------------------------------------------------
# the bounded participation sketch (fairness without a dense histogram)
# ---------------------------------------------------------------------------


class SpaceSavingSketch:
    """Metwally et al. space-saving heavy-hitter sketch, capacity-k:
    the top participating clients by (over-)estimated count. At
    capacity the minimum-count row (ties broken by smallest id —
    deterministic) is replaced and inherits its count, so heavy
    participants can never be evicted by light ones. Memory is O(k)
    regardless of how many distinct clients participate."""

    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError(f"sketch capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.counts: Dict[int, int] = {}
        self.total = 0

    def add(self, ids) -> None:
        for i in np.asarray(ids, np.int64).reshape(-1):
            i = int(i)
            self.total += 1
            if i in self.counts:
                self.counts[i] += 1
            elif len(self.counts) < self.capacity:
                self.counts[i] = 1
            else:
                victim = min(self.counts, key=lambda c: (self.counts[c], c))
                self.counts[i] = self.counts.pop(victim) + 1

    def top(self, k: int) -> List[Tuple[int, int]]:
        return sorted(
            self.counts.items(), key=lambda kv: (-kv[1], kv[0])
        )[: max(0, int(k))]

    def gini(self) -> float:
        """Gini coefficient over the SKETCH rows (documented: the
        fairness view of the top-k participants, not the full — and
        deliberately never materialized — [num_clients] histogram)."""
        x = np.sort(np.asarray(list(self.counts.values()), np.float64))
        n = len(x)
        s = x.sum()
        if n == 0 or s <= 0:
            return 0.0
        i = np.arange(1, n + 1, dtype=np.float64)
        return float(round(2.0 * np.sum(i * x) / (n * s) - (n + 1.0) / n, 6))

    def max_share(self) -> float:
        if not self.counts or not self.total:
            return 0.0
        return float(round(max(self.counts.values()) / self.total, 6))


# ---------------------------------------------------------------------------
# the per-fit tracker the driver feeds
# ---------------------------------------------------------------------------


class PopulationTracker:
    """Per-fit accumulator behind ``population_health`` records.

    The driver feeds it host-side facts it already has — the realized
    cohort (:meth:`observe_cohort`, pads and zero-weight dropouts
    excluded), the stream-slab dedup shape (:meth:`observe_slab`), the
    streaming sketch refresh (:meth:`observe_sketch_refresh`) — and at
    every metrics-flush boundary :meth:`window_record` folds the window
    plus pager/store deltas into one JSONL record and resets. Coverage,
    fairness, and the pager/store lifetime totals are cumulative;
    everything else is per-window. All structures are fixed-size or
    O(cohort) per round, and all mutation happens on the fit thread —
    the worker-thread paths (store gathers) count inside the
    instrumented objects themselves and are only *read* here."""

    def __init__(self, num_clients: int, top_k: int = 64,
                 hll_bits: int = 12, recency_capacity: int = 8192):
        self.num_clients = int(num_clients)
        self.coverage = HLLCounter(hll_bits)
        self.fairness = SpaceSavingSketch(top_k)
        # bounded last-participation-round map (LRU by insertion order
        # refresh): cohort members absent from it — first-timers, or
        # evicted long-agos — count in `staleness.unknown` rather than
        # skewing the distribution
        from collections import OrderedDict

        self._recency: "OrderedDict[int, int]" = OrderedDict()
        self._recency_cap = max(1, int(recency_capacity))
        # window accumulators (reset by window_record)
        self._w_rounds = 0
        self._w_participants = 0
        self._w_draws: Dict[str, int] = {}
        self._w_stale: List[int] = []
        self._w_first_seen = 0
        self._w_unknown = 0
        self._w_slab_indexed = 0
        self._w_slab_unique = 0
        self._sketch_flag_cov: Optional[float] = None
        # async (fedbuff) window accumulators: realized staleness
        # distribution, admitted-update count, clamp + backpressure
        # totals — fed by the scheduler, folded as the "async" section
        self._w_async_stale: List[float] = []
        self._w_async_max_stale = 0
        self._w_async_steps = 0
        self._w_async_absorbed = 0
        self._w_async_clamped = 0
        self._w_bp_dropped = 0
        self._w_bp_rejected = 0
        # multi-version / hierarchy window accumulators: per-version
        # absorbed counts (server.async_versions > 1), retired-
        # generation re-admissions, and crashed-edge exclusions
        # (server.hierarchy under fedbuff)
        self._w_async_versions: Dict[int, int] = {}
        self._w_async_readmitted = 0
        self._w_edge_crashed = 0
        # churn window accumulators (run.churn realized failures) —
        # fed at flush from the per-round failure stats
        self._w_churn = {"unavailable": 0, "dropped": 0, "crashed": 0}
        self._w_churn_seen = False
        # lifetime baselines for delta-ing the instrumented objects
        self._pager_base = {
            "hits": 0, "misses": 0, "page_ins": 0, "evictions": 0,
            "page_syncs": 0, "sync_ms": 0.0,
        }
        self._store_base: Optional[Dict[str, Any]] = None

    # ---- feeds -------------------------------------------------------

    def observe_cohort(self, round_idx: int, cohort, n_ex,
                       draw_counts: Optional[Dict[str, int]] = None) -> None:
        """One dispatched round's realized participants: ``cohort`` may
        carry poisson pad slots (id == num_clients) and ``n_ex`` zeros
        for dropouts — both are excluded, so "participation" means a
        row that carried aggregation weight."""
        ids = np.asarray(cohort, np.int64).reshape(-1)
        w = np.asarray(n_ex).reshape(-1)
        real = ids[(ids >= 0) & (ids < self.num_clients) & (w > 0)]
        self._w_rounds += 1
        self._w_participants += int(real.size)
        if draw_counts:
            for k, v in draw_counts.items():
                self._w_draws[k] = self._w_draws.get(k, 0) + int(v)
        self.coverage.add(real)
        self.fairness.add(real)
        r = int(round_idx)
        for c in real:
            c = int(c)
            last = self._recency.pop(c, None)
            if last is None:
                if len(self._recency) >= self._recency_cap:
                    self._recency.popitem(last=False)
                    self._w_unknown += 1
                else:
                    self._w_first_seen += 1
            else:
                self._w_stale.append(r - last)
            self._recency[c] = r

    def observe_slab(self, rows_indexed: int, rows_unique: int) -> None:
        """One round's (or fused chunk's) stream-slab gather shape: how
        many grid slots indexed the corpus vs how many unique example
        rows were actually gathered — the dedup ratio is the fraction
        of gather I/O the union slab saved."""
        self._w_slab_indexed += int(rows_indexed)
        self._w_slab_unique += int(rows_unique)

    def observe_async(self, round_idx: int, staleness, *, absorbed: int,
                      clamped: int = 0, bp_dropped: int = 0,
                      bp_rejected: int = 0, readmitted: int = 0,
                      edge_crashed: int = 0,
                      version: Optional[int] = None) -> None:
        """One fedbuff server step's scheduler facts: the popped
        buffer's realized staleness values, how many updates carried
        weight (arrival-rate numerator), and the clamp/backpressure
        counts. ``version`` is the model line this step drove
        (server.async_versions > 1), ``readmitted`` late completions
        folded back from a retired generation, ``edge_crashed`` edge
        aggregators lost this step (server.hierarchy). Pure
        observation on the fit thread (the async scheduler is never
        double-buffered)."""
        s = np.asarray(staleness, np.float64).reshape(-1)
        self._w_async_steps += 1
        self._w_async_absorbed += int(absorbed)
        self._w_async_clamped += int(clamped)
        self._w_bp_dropped += int(bp_dropped)
        self._w_bp_rejected += int(bp_rejected)
        self._w_async_readmitted += int(readmitted)
        self._w_edge_crashed += int(edge_crashed)
        if version is not None:
            v = int(version)
            self._w_async_versions[v] = (
                self._w_async_versions.get(v, 0) + int(absorbed)
            )
        if s.size:
            self._w_async_stale.append(float(s.mean()))
            self._w_async_max_stale = max(
                self._w_async_max_stale, int(s.max())
            )

    def observe_churn(self, unavailable: int, dropped: int,
                      crashed: int) -> None:
        """One round's realized churn failures (run.churn): offline at
        dispatch, hazard-dropped, crashed mid-round — counts only, fed
        at metrics-flush from the per-round failure stats (fit
        thread)."""
        self._w_churn_seen = True
        self._w_churn["unavailable"] += int(unavailable)
        self._w_churn["dropped"] += int(dropped)
        self._w_churn["crashed"] += int(crashed)

    def observe_sketch_refresh(self, total_flagged: float,
                               kept_flagged: float) -> None:
        """Streaming-mode sketch refresh: what fraction of the ledger's
        total flagged mass the retained sketch rows carry — 1.0 means
        the sketch covers every flag-bearing (attacker-evidence) client,
        low values mean the flag suppression cannot see the attackers."""
        self._sketch_flag_cov = (
            round(float(kept_flagged) / float(total_flagged), 6)
            if total_flagged > 0 else None
        )

    # ---- window fold -------------------------------------------------

    @staticmethod
    def _pager_counters(pager) -> Dict[str, float]:
        return {
            "hits": int(pager.hits), "misses": int(pager.misses),
            "page_ins": int(pager.page_ins),
            "evictions": int(pager.evictions),
            "page_syncs": int(pager.page_syncs),
            "sync_ms": float(pager.sync_ms),
        }

    def window_record(self, last_round: int, *, pager=None,
                      store_arrays=(), sketch_ids=None,
                      refresh_age: Optional[int] = None,
                      ) -> Optional[Dict[str, Any]]:
        """Fold the window into one ``population_health`` record (None
        when the window saw no rounds — tail flushes must not emit
        empty records). Count-based fields are engine-parity material;
        wall-clock fields all end in ``_ms``."""
        if self._w_rounds == 0:
            return None
        est = self.coverage.estimate()
        rec: Dict[str, Any] = {
            "event": "population_health",
            "round": int(last_round),
            "window_rounds": self._w_rounds,
            "participants": self._w_participants,
            "coverage": {
                "unique_clients_est": est,
                "coverage_pct": round(
                    100.0 * min(est, self.num_clients) / self.num_clients, 2
                ),
                "num_clients": self.num_clients,
            },
            "fairness": {
                "total_participations": self.fairness.total,
                "tracked": len(self.fairness.counts),
                "gini": self.fairness.gini(),
                "max_share": self.fairness.max_share(),
                "top_clients": [
                    [int(c), int(n)] for c, n in self.fairness.top(5)
                ],
            },
        }
        if self._w_draws:
            rec["draws"] = dict(sorted(self._w_draws.items()))
        stale = {
            "first_seen": self._w_first_seen,
            "known": len(self._w_stale),
        }
        if self._w_unknown:
            stale["unknown"] = self._w_unknown
        if self._w_stale:
            s = np.asarray(self._w_stale, np.float64)
            stale.update({
                "mean": round(float(s.mean()), 3),
                "p50": round(float(np.median(s)), 1),
                "max": int(s.max()),
            })
        rec["staleness"] = stale
        if sketch_ids is not None:
            live = int(np.count_nonzero(np.asarray(sketch_ids) >= 0))
            rec["sketch"] = {
                "rows": live,
                "occupancy": round(live / max(1, len(sketch_ids)), 4),
            }
            if refresh_age is not None:
                rec["sketch"]["refresh_age"] = int(refresh_age)
            if self._sketch_flag_cov is not None:
                rec["sketch"]["flag_coverage"] = self._sketch_flag_cov
        if pager is not None:
            cur = self._pager_counters(pager)
            delta = {k: cur[k] - self._pager_base[k] for k in cur}
            self._pager_base = cur
            looked = delta["hits"] + delta["misses"]
            rec["pager"] = {
                "hits": int(delta["hits"]),
                "misses": int(delta["misses"]),
                "hit_rate": round(delta["hits"] / looked, 4) if looked else 1.0,
                "page_ins": int(delta["page_ins"]),
                "evictions": int(delta["evictions"]),
                "page_syncs": int(delta["page_syncs"]),
                "sync_stall_ms": round(delta["sync_ms"], 3),
            }
        store_stats = [
            a.gather_stats() for a in store_arrays
            if hasattr(a, "gather_stats")
        ]
        if store_stats:
            cur_s = {
                "calls": sum(s["calls"] for s in store_stats),
                "rows": sum(s["rows"] for s in store_stats),
                "bytes": sum(s["bytes"] for s in store_stats),
                "ms": sum(s["ms"] for s in store_stats),
                "io_ms": sum(s.get("io_ms", 0.0) for s in store_stats),
                "pool_gathers": sum(
                    s.get("pool_gathers", 0) for s in store_stats
                ),
                "replica_rows": sum(
                    s.get("replica_rows", 0) for s in store_stats
                ),
            }
            touches = [np.asarray(s["shard_touches"]) for s in store_stats]
            width = max(len(t) for t in touches)
            tot_touch = np.zeros(width, np.int64)
            for t in touches:
                tot_touch[: len(t)] += t
            if self._store_base is None:
                self._store_base = {
                    "calls": 0, "rows": 0, "bytes": 0, "ms": 0.0,
                    "io_ms": 0.0, "pool_gathers": 0, "replica_rows": 0,
                    "touches": np.zeros(width, np.int64),
                }
            base = self._store_base
            rec["store"] = {
                "gather_calls": int(cur_s["calls"] - base["calls"]),
                "rows_gathered": int(cur_s["rows"] - base["rows"]),
                "bytes_gathered": int(cur_s["bytes"] - base["bytes"]),
                "gather_ms": round(cur_s["ms"] - base["ms"], 3),
                # summed per-shard copy time vs the wall gather_ms: the
                # pool's overlap factor reads directly off the pair
                # (io_ms ≈ gather_ms → serial; io_ms >> gather_ms →
                # the worker pool is hiding shard I/O)
                "gather_io_ms": round(
                    cur_s["io_ms"] - base.get("io_ms", 0.0), 3
                ),
                "gather_workers": max(
                    int(s.get("workers", 1)) for s in store_stats
                ),
                "pool_gathers": int(
                    cur_s["pool_gathers"] - base.get("pool_gathers", 0)
                ),
                "shard_touches": [
                    int(v) for v in (tot_touch - base["touches"])
                ],
            }
            replica = int(
                cur_s["replica_rows"] - base.get("replica_rows", 0)
            )
            if replica:
                # multi-host ownership: rows served from NON-owned
                # shards via read-replica fallback this window
                rec["store"]["replica_rows"] = replica
            self._store_base = dict(cur_s, touches=tot_touch)
        if self._w_slab_indexed:
            rec.setdefault("store", {}).update({
                "slab_rows_indexed": self._w_slab_indexed,
                "slab_rows_unique": self._w_slab_unique,
                "slab_dedup_ratio": round(
                    self._w_slab_unique / self._w_slab_indexed, 4
                ),
            })
        if self._w_async_steps:
            # the fedbuff production-traffic panel: arrival rate
            # (absorbed updates per server step), the realized
            # staleness distribution, and clamp/backpressure counts
            a: Dict[str, Any] = {
                "server_steps": self._w_async_steps,
                "updates_absorbed": self._w_async_absorbed,
                "arrival_rate": round(
                    self._w_async_absorbed / self._w_async_steps, 3
                ),
                "staleness_max": self._w_async_max_stale,
            }
            if self._w_async_stale:
                s = np.asarray(self._w_async_stale, np.float64)
                a["staleness_mean"] = round(float(s.mean()), 3)
                a["staleness_p90"] = round(float(np.percentile(s, 90)), 3)
            if self._w_async_clamped:
                a["staleness_clamped"] = self._w_async_clamped
            if self._w_bp_dropped:
                a["backpressure_dropped"] = self._w_bp_dropped
            if self._w_bp_rejected:
                a["backpressure_rejected"] = self._w_bp_rejected
            if self._w_async_versions:
                # per-model-line absorbed counts for this window — the
                # multi-version health panel (a starved line shows up
                # as a near-zero bucket here long before its loss does)
                a["per_version_absorbed"] = {
                    str(v): int(n)
                    for v, n in sorted(self._w_async_versions.items())
                }
            if self._w_async_readmitted:
                a["version_readmitted"] = self._w_async_readmitted
            if self._w_edge_crashed:
                a["edge_crashed"] = self._w_edge_crashed
            rec["async"] = a
        if self._w_churn_seen:
            rec["churn"] = {k: int(v) for k, v in self._w_churn.items()}
        # reset the window
        self._w_rounds = 0
        self._w_participants = 0
        self._w_draws = {}
        self._w_stale = []
        self._w_first_seen = 0
        self._w_unknown = 0
        self._w_slab_indexed = 0
        self._w_slab_unique = 0
        self._w_async_stale = []
        self._w_async_max_stale = 0
        self._w_async_steps = 0
        self._w_async_absorbed = 0
        self._w_async_clamped = 0
        self._w_bp_dropped = 0
        self._w_bp_rejected = 0
        self._w_async_versions = {}
        self._w_async_readmitted = 0
        self._w_edge_crashed = 0
        self._w_churn = {"unavailable": 0, "dropped": 0, "crashed": 0}
        self._w_churn_seen = False
        return rec

    def summary_totals(self, pager=None, store_arrays=()) -> Dict[str, Any]:
        """The population keys ``run_summary`` carries (and ``colearn
        summarize`` renders): lifetime coverage and participation, plus
        the LIVE pager hit rate and store gather bytes (read from the
        instrumented objects directly — the last flush window may have
        folded before the final round landed)."""
        est = self.coverage.estimate()
        out: Dict[str, Any] = {
            "population_unique_clients": est,
            "population_coverage_pct": round(
                100.0 * min(est, self.num_clients) / self.num_clients, 2
            ),
            "population_participations": int(self.fairness.total),
        }
        if pager is not None:
            looked = int(pager.hits) + int(pager.misses)
            if looked:
                out["pager_hit_rate"] = round(int(pager.hits) / looked, 4)
        stats = [
            a.gather_stats() for a in store_arrays
            if hasattr(a, "gather_stats")
        ]
        total_bytes = sum(s["bytes"] for s in stats)
        if total_bytes:
            out["store_gather_bytes"] = int(total_bytes)
            total_ms = sum(s["ms"] for s in stats)
            if total_ms:
                # wall-clock store throughput — the budget-gated
                # data-plane headline (BENCH_BUDGETS
                # store_gather_mbps_min via `colearn bench-report`)
                out["store_gather_mbps"] = round(
                    total_bytes / (1 << 20) / (total_ms / 1e3), 1
                )
            out["store_gather_workers"] = max(
                int(s.get("workers", 1)) for s in stats
            )
        return out


# ---------------------------------------------------------------------------
# incremental JSONL tailing (`colearn watch` — pure host, no backend)
# ---------------------------------------------------------------------------


def read_complete_records(path: str, offset: int = 0
                          ) -> Tuple[List[Dict[str, Any]], int]:
    """Read every COMPLETE record line past ``offset``; return
    ``(records, new_offset)``. A live writer's torn tail — the final
    line without a terminating newline, possibly truncated mid-record —
    is left unconsumed (the offset stays before it) so the next poll
    rereads it whole; an unparsable *terminated* line (a crash artifact)
    is skipped, matching ``summary.load_records``."""
    with open(path, "rb") as f:
        f.seek(offset)
        data = f.read()
    end = data.rfind(b"\n")
    if end < 0:
        return [], offset
    records: List[Dict[str, Any]] = []
    for line in data[: end + 1].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except (json.JSONDecodeError, UnicodeDecodeError):
            continue
    return records, offset + end + 1


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 24) -> str:
    """Unicode block sparkline of the TAIL of a numeric series (empty
    string for no data; a flat series renders mid-blocks)."""
    vals = [float(v) for v in values][-max(1, int(width)):]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_BLOCKS[3] * len(vals)
    scale = (len(_SPARK_BLOCKS) - 1) / (hi - lo)
    return "".join(
        _SPARK_BLOCKS[int(round((v - lo) * scale))] for v in vals
    )


def watch_snapshot(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a run's records (complete or mid-fit) into the live-view
    state ``colearn watch`` renders: run state, loss / rounds-per-sec
    series, health + divergence counts, the latest population-health
    coverage and pager hit rate, and per-phase ms series for the
    sparklines. Pure host; tolerant of every historical record shape
    (missing families render as absent keys, never KeyError)."""
    snap: Dict[str, Any] = {
        "state": "running",
        "rounds": 0,
        "loss_series": [],
        "rps_series": [],
        "health": {},
        "phase_ms": {},
    }
    phase_totals: Dict[str, float] = {}
    last_pop = None
    for rec in records:
        ev = rec.get("event")
        if ev == "run_summary":
            snap["state"] = "completed"
            snap["rounds"] = max(snap["rounds"], int(rec.get("rounds", 0)))
            if "wall_time_sec" in rec:
                snap["wall_time_sec"] = float(rec["wall_time_sec"])
            for k in ("population_coverage_pct", "population_unique_clients",
                      "pager_hit_rate", "ledger_evictions",
                      "ledger_page_syncs", "async_updates_per_sec",
                      "async_updates_absorbed", "staleness_clamped",
                      "backpressure_dropped", "backpressure_rejected",
                      "async_staleness_p50", "async_staleness_p90",
                      "async_staleness_max", "async_per_version",
                      "version_readmitted", "hier_edges",
                      "hier_edge_absorbed", "hier_edge_crashed"):
                if k in rec:
                    snap[k] = rec[k]
            continue
        if ev == "health":
            kind = rec.get("kind", "?")
            snap["health"][kind] = snap["health"].get(kind, 0) + 1
            continue
        if ev == "spans":
            for name, agg in (rec.get("phases") or {}).items():
                cnt = int(agg.get("count", 0)) or 1
                mean = float(agg.get("total_ms", 0.0)) / cnt
                snap["phase_ms"].setdefault(name, []).append(round(mean, 3))
                phase_totals[name] = (
                    phase_totals.get(name, 0.0)
                    + float(agg.get("total_ms", 0.0))
                )
            continue
        if ev == "population_health":
            last_pop = rec
            continue
        if ev == "precision":
            snap["precision"] = {
                k: rec.get(k) for k in
                ("param_dtype", "compute_dtype", "local_param_dtype")
                if k in rec
            }
            continue
        if ev is None and "round" in rec:
            snap["rounds"] = max(snap["rounds"], int(rec["round"]))
            if "train_loss" in rec:
                snap["loss_series"].append(float(rec["train_loss"]))
                snap["last_train_loss"] = float(rec["train_loss"])
            if "rounds_per_sec" in rec:
                snap["rps_series"].append(float(rec["rounds_per_sec"]))
                snap["rounds_per_sec"] = float(rec["rounds_per_sec"])
            if "mean_staleness" in rec:
                # the fedbuff staleness-distribution panel's series
                snap.setdefault("staleness_series", []).append(
                    float(rec["mean_staleness"])
                )
            for k in ("eval_loss", "eval_acc"):
                if k in rec:
                    snap.setdefault("eval", {})[k] = float(rec[k])
    if last_pop is not None:
        cov = last_pop.get("coverage") or {}
        if "coverage_pct" in cov:
            snap["coverage_pct"] = cov["coverage_pct"]
            snap["unique_clients_est"] = cov.get("unique_clients_est")
        pager = last_pop.get("pager")
        if pager:
            snap["pager_window"] = {
                k: pager.get(k) for k in
                ("hit_rate", "page_ins", "evictions", "page_syncs")
                if k in pager
            }
        sketch = last_pop.get("sketch")
        if sketch:
            snap["sketch"] = sketch
        asy = last_pop.get("async")
        if asy:
            # arrival-rate / staleness-distribution / backpressure
            # panel (fedbuff under production traffic)
            snap["async"] = asy
        chn = last_pop.get("churn")
        if chn:
            snap["churn"] = chn
    # keep the series bounded for --json consumers and the sparklines
    snap["loss_series"] = snap["loss_series"][-64:]
    snap["rps_series"] = snap["rps_series"][-64:]
    if "staleness_series" in snap:
        snap["staleness_series"] = snap["staleness_series"][-64:]
    # top phases by cumulative time, round-loop family first
    top = sorted(phase_totals, key=lambda n: -phase_totals[n])[:5]
    snap["phase_ms"] = {
        n: snap["phase_ms"][n][-32:] for n in top
    }
    # determinism flight recorder status (run.obs.digest): last
    # verified digest round, chain OK/broken, and any failed resume
    # verification — absent key when the run logs no digests
    from colearn_federated_learning_tpu.obs.digest import (
        watch_digest_status,
    )
    dg = watch_digest_status(records)
    if dg is not None:
        snap["digest"] = dg
    return snap


def format_watch(snap: Dict[str, Any], path: str = "") -> str:
    """Render one watch frame as aligned text with sparklines."""
    lines = []
    state = snap.get("state", "running").upper()
    head = f"watch: {path}" if path else "watch"
    head += f"  [{state}]  round {snap.get('rounds', 0)}"
    if "rounds_per_sec" in snap:
        head += f"  rounds/sec {snap['rounds_per_sec']:.3f}"
    if "wall_time_sec" in snap:
        head += f"  wall {snap['wall_time_sec']:.1f}s"
    lines.append(head)
    if "last_train_loss" in snap:
        line = (
            f"loss  {snap['last_train_loss']:<10.4g}"
            f"{sparkline(snap.get('loss_series', ()))}"
        )
        ev = snap.get("eval")
        if ev:
            line += "   " + "  ".join(
                f"{k}={v:.4f}" for k, v in sorted(ev.items())
            )
        lines.append(line)
    if snap.get("rps_series"):
        lines.append(
            f"r/s   {snap.get('rounds_per_sec', 0.0):<10.3f}"
            f"{sparkline(snap['rps_series'])}"
        )
    health = snap.get("health") or {}
    lines.append(
        "health: " + (
            ", ".join(f"{k}×{v}" for k, v in sorted(health.items()))
            if health else "ok"
        )
    )
    dg = snap.get("digest")
    if dg:
        # flight-recorder status line: the chain verdict is recomputed
        # from the log every frame, so tampering/truncation shows up
        # live, not only at the next resume
        line = (
            f"digest: chain {'OK' if dg.get('chain_ok') else 'BROKEN'}"
            f" through round {dg.get('last_round', 0)}"
        )
        if not dg.get("chain_ok") and dg.get("problems"):
            line += f"  [{dg['problems'][0]}]"
        rf = dg.get("resume_fail")
        if rf:
            line += (
                f"  RESUME-VERIFY FAILED @ round {rf.get('round')}"
                f" ({rf.get('detail', '')})"
            )
        lines.append(line)
    asy = snap.get("async")
    if asy or snap.get("staleness_series"):
        # production-traffic panel: arrival rate, staleness
        # distribution (+ sparkline of the per-round means), clamp and
        # backpressure counters — the fedbuff ops view under churn
        parts = []
        if asy and "arrival_rate" in asy:
            parts.append(f"arrivals {asy['arrival_rate']:.1f} upd/step")
        if asy and "staleness_mean" in asy:
            line = f"staleness {asy['staleness_mean']:.2f}"
            if "staleness_p90" in asy:
                line += f"/p90 {asy['staleness_p90']:.2f}"
            if "staleness_max" in asy:
                line += f"/max {asy['staleness_max']}"
            parts.append(line)
        clamped = (asy or {}).get(
            "staleness_clamped", snap.get("staleness_clamped")
        )
        if clamped:
            parts.append(f"clamped {clamped}")
        bp = ((asy or {}).get("backpressure_dropped", 0)
              + (asy or {}).get("backpressure_rejected", 0)) or (
            (snap.get("backpressure_dropped") or 0)
            + (snap.get("backpressure_rejected") or 0)
        )
        if bp:
            parts.append(f"backpressure {bp}")
        if "async_updates_per_sec" in snap:
            parts.append(f"{snap['async_updates_per_sec']:.1f} upd/s")
        line = "async: " + ("  ".join(parts) if parts else "ok")
        series = snap.get("staleness_series")
        if series:
            line += "  " + sparkline(series)
        lines.append(line)
        # multi-version lines: absorbed per model line this window
        # (a starved line reads ~0 here) plus retired-generation
        # re-admissions; hierarchy: crashed-edge exclusions
        pv = (asy or {}).get(
            "per_version_absorbed", snap.get("async_per_version")
        )
        if pv:
            vparts = [
                f"v{v} {n}" for v, n in sorted(
                    pv.items(), key=lambda kv: int(kv[0])
                )
            ]
            readmit = (asy or {}).get(
                "version_readmitted", snap.get("version_readmitted")
            )
            if readmit:
                vparts.append(f"readmitted {readmit}")
            lines.append("versions: " + "  ".join(vparts))
        crashed_e = (asy or {}).get(
            "edge_crashed", snap.get("hier_edge_crashed")
        )
        if crashed_e:
            lines.append(f"edges: crashed {crashed_e}")
    chn = snap.get("churn")
    if chn:
        lines.append(
            "churn: " + "  ".join(
                f"{k} {v}" for k, v in sorted(chn.items()) if v
            )
        )
    bits = []
    if "coverage_pct" in snap:
        bits.append(f"coverage {snap['coverage_pct']:.1f}%")
    pw = snap.get("pager_window")
    if pw and "hit_rate" in pw:
        bits.append(f"pager hit rate {100.0 * pw['hit_rate']:.1f}%")
    elif "pager_hit_rate" in snap:
        bits.append(f"pager hit rate {100.0 * snap['pager_hit_rate']:.1f}%")
    sk = snap.get("sketch")
    if sk and "occupancy" in sk:
        bits.append(f"sketch occupancy {100.0 * sk['occupancy']:.1f}%")
    if bits:
        lines.append("population: " + "  ".join(bits))
    phases = snap.get("phase_ms") or {}
    if phases:
        lines.append("phase ms (per-window mean):")
        for name, series in phases.items():
            last = series[-1] if series else 0.0
            lines.append(f"  {name:<24}{last:>9.2f}  {sparkline(series)}")
    return "\n".join(lines)


def watch_follow(path: str, interval: float = 2.0, out=None,
                 max_refreshes: Optional[int] = None,
                 clear_screen: Optional[bool] = None) -> int:
    """The live loop behind ``colearn watch``: incremental-tail the
    JSONL, re-render each ``interval`` seconds, stop when the run
    completes (a ``run_summary`` record lands) or after
    ``max_refreshes`` frames (tests / bounded watches). Returns the
    process exit code — 2 when the log never produced a record,
    matching the ``summarize`` empty-log contract."""
    out = out or sys.stdout
    if clear_screen is None:
        clear_screen = hasattr(out, "isatty") and out.isatty()
    offset = 0
    records: List[Dict[str, Any]] = []
    frames = 0
    while True:
        try:
            new, offset = read_complete_records(path, offset)
        except FileNotFoundError:
            new = []
        records.extend(new)
        frames += 1
        if records:
            frame = format_watch(watch_snapshot(records), path)
            if clear_screen:
                out.write("\x1b[2J\x1b[H")
            out.write(frame + "\n")
            out.flush()
            if watch_snapshot(records)["state"] == "completed":
                return 0
        if max_refreshes is not None and frames >= max_refreshes:
            return 0 if records else 2
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0 if records else 2


# ---------------------------------------------------------------------------
# `colearn population` — the post-hoc report twin
# ---------------------------------------------------------------------------


def population_report(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a run's ``population_health`` records into the post-hoc
    data-plane report: coverage trajectory, draw-split totals, pager
    and store totals with overall rates, slab dedup, staleness, and the
    final fairness view. Raises ValueError (→ CLI exit 2) when the run
    carried no population records."""
    recs = [r for r in records if r.get("event") == "population_health"]
    if not recs:
        raise ValueError(
            "no population_health records in this run — enable the "
            "federation health observatory with "
            "run.obs.population.enabled=true"
        )
    draws: Dict[str, int] = {}
    pager = {"hits": 0, "misses": 0, "page_ins": 0, "evictions": 0,
             "page_syncs": 0, "sync_stall_ms": 0.0}
    store = {"gather_calls": 0, "rows_gathered": 0, "bytes_gathered": 0,
             "gather_ms": 0.0, "gather_io_ms": 0.0, "pool_gathers": 0,
             "replica_rows": 0, "slab_rows_indexed": 0,
             "slab_rows_unique": 0}
    shard_touches: List[int] = []
    rounds = participants = 0
    gather_workers = 0
    cov_series: List[float] = []
    saw_pager = saw_store = False
    asy = {"server_steps": 0, "updates_absorbed": 0, "staleness_max": 0,
           "staleness_clamped": 0, "backpressure_dropped": 0,
           "backpressure_rejected": 0}
    stale_means: List[float] = []
    churn = {"unavailable": 0, "dropped": 0, "crashed": 0}
    saw_async = saw_churn = False
    for r in recs:
        rounds += int(r.get("window_rounds", 0))
        participants += int(r.get("participants", 0))
        a = r.get("async")
        if a:
            saw_async = True
            for k in ("server_steps", "updates_absorbed",
                      "staleness_clamped", "backpressure_dropped",
                      "backpressure_rejected"):
                asy[k] += int(a.get(k, 0))
            asy["staleness_max"] = max(
                asy["staleness_max"], int(a.get("staleness_max", 0))
            )
            if "staleness_mean" in a:
                stale_means.append(float(a["staleness_mean"]))
        c = r.get("churn")
        if c:
            saw_churn = True
            for k in churn:
                churn[k] += int(c.get(k, 0))
        for k, v in (r.get("draws") or {}).items():
            draws[k] = draws.get(k, 0) + int(v)
        cov = r.get("coverage") or {}
        if "coverage_pct" in cov:
            cov_series.append(float(cov["coverage_pct"]))
        p = r.get("pager")
        if p:
            saw_pager = True
            for k in pager:
                pager[k] += p.get(k, 0)
        s = r.get("store")
        if s:
            saw_store = True
            for k in store:
                store[k] += s.get(k, 0)
            gather_workers = max(
                gather_workers, int(s.get("gather_workers", 0))
            )
            for i, t in enumerate(s.get("shard_touches") or []):
                while len(shard_touches) <= i:
                    shard_touches.append(0)
                shard_touches[i] += int(t)
    last = recs[-1]
    report: Dict[str, Any] = {
        "windows": len(recs),
        "rounds": rounds,
        "participants": participants,
        "coverage": last.get("coverage") or {},
        "coverage_pct_series": cov_series,
        "fairness": last.get("fairness") or {},
        "staleness": last.get("staleness") or {},
    }
    if draws:
        report["draws"] = dict(sorted(draws.items()))
    if saw_async:
        if asy["server_steps"]:
            asy["arrival_rate"] = round(
                asy["updates_absorbed"] / asy["server_steps"], 3
            )
        if stale_means:
            asy["staleness_mean"] = round(
                float(np.mean(stale_means)), 3
            )
        report["async"] = asy
    if saw_churn:
        report["churn"] = churn
    if "sketch" in last:
        report["sketch"] = last["sketch"]
    if saw_pager:
        looked = pager["hits"] + pager["misses"]
        report["pager"] = dict(
            pager,
            hit_rate=round(pager["hits"] / looked, 4) if looked else 1.0,
        )
    if saw_store:
        report["store"] = dict(store)
        if gather_workers:
            report["store"]["gather_workers"] = gather_workers
        if store["gather_ms"]:
            # wall-clock gather throughput — the data-plane headline
            # (`store_gather_mbps`, budget-gated by `colearn bench-report`)
            report["store"]["store_gather_mbps"] = round(
                store["bytes_gathered"] / (1 << 20)
                / (store["gather_ms"] / 1e3), 1
            )
        if shard_touches:
            report["store"]["shard_touches"] = shard_touches
        if store["slab_rows_indexed"]:
            report["store"]["slab_dedup_ratio"] = round(
                store["slab_rows_unique"] / store["slab_rows_indexed"], 4
            )
    return report


def _fmt_bytes(n) -> str:
    v = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if v < 1024.0 or unit == "TiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024.0


def format_population_report(report: Dict[str, Any], path: str = "") -> str:
    """Render the population report as aligned text."""
    lines = []
    head = f"run: {path}" if path else "population health"
    head += (
        f"  windows: {report['windows']}  rounds: {report['rounds']}"
        f"  participations: {report['participants']}"
    )
    lines.append(head)
    cov = report.get("coverage") or {}
    if cov:
        lines.append(
            f"coverage: {cov.get('unique_clients_est', 0)} of "
            f"{cov.get('num_clients', 0)} clients "
            f"({cov.get('coverage_pct', 0.0):.1f}%)  "
            f"{sparkline(report.get('coverage_pct_series', ()))}"
        )
    draws = report.get("draws")
    if draws:
        total = sum(draws.values()) or 1
        lines.append("draw split: " + "  ".join(
            f"{k} {v} ({100.0 * v / total:.0f}%)"
            for k, v in draws.items()
        ))
    sk = report.get("sketch")
    if sk:
        bits = [f"rows {sk.get('rows', 0)}",
                f"occupancy {100.0 * sk.get('occupancy', 0.0):.1f}%"]
        if "refresh_age" in sk:
            bits.append(f"refresh age {sk['refresh_age']} rounds")
        if "flag_coverage" in sk:
            bits.append(f"flag coverage {100.0 * sk['flag_coverage']:.1f}%")
        lines.append("score sketch: " + "  ".join(bits))
    st = report.get("staleness")
    if st and st.get("known"):
        lines.append(
            f"staleness (rounds since last participation): mean "
            f"{st.get('mean', 0.0):.1f}  p50 {st.get('p50', 0.0):.0f}  max "
            f"{st.get('max', 0)}  (+{st.get('first_seen', 0)} first-time)"
        )
    asy = report.get("async")
    if asy:
        line = (
            f"async traffic: {asy.get('updates_absorbed', 0)} updates "
            f"over {asy.get('server_steps', 0)} server steps"
        )
        if "arrival_rate" in asy:
            line += f" ({asy['arrival_rate']:.1f} upd/step)"
        if "staleness_mean" in asy:
            line += (
                f"  staleness mean {asy['staleness_mean']:.2f} "
                f"max {asy.get('staleness_max', 0)}"
            )
        bits = []
        if asy.get("staleness_clamped"):
            bits.append(f"clamped {asy['staleness_clamped']}")
        if asy.get("backpressure_dropped"):
            bits.append(f"bp-dropped {asy['backpressure_dropped']}")
        if asy.get("backpressure_rejected"):
            bits.append(f"bp-rejected {asy['backpressure_rejected']}")
        if bits:
            line += "  " + "  ".join(bits)
        lines.append(line)
    chn = report.get("churn")
    if chn:
        lines.append(
            "churn: " + "  ".join(
                f"{k} {v}" for k, v in sorted(chn.items())
            )
        )
    pg = report.get("pager")
    if pg:
        lines.append(
            f"ledger pager: hit rate {100.0 * pg['hit_rate']:.1f}% "
            f"({pg['hits']} hits / {pg['misses']} misses)  page-ins "
            f"{pg['page_ins']}  evictions {pg['evictions']}  syncs "
            f"{pg['page_syncs']} ({pg['sync_stall_ms']:.1f} ms stalled)"
        )
    st = report.get("store")
    if st:
        line = (
            f"store I/O: {_fmt_bytes(st.get('bytes_gathered', 0))} gathered "
            f"in {st.get('gather_calls', 0)} gathers "
            f"({st.get('gather_ms', 0.0):.1f} ms)"
        )
        if "store_gather_mbps" in st:
            line += f"  {st['store_gather_mbps']:.0f} MiB/s"
        if st.get("gather_workers", 0) > 1:
            line += (
                f"  pool x{st['gather_workers']} "
                f"(io {st.get('gather_io_ms', 0.0):.1f} ms summed)"
            )
        if st.get("replica_rows"):
            line += f"  replica rows {st['replica_rows']}"
        if "slab_dedup_ratio" in st:
            line += (
                f"  slab dedup {st['slab_dedup_ratio']:.2f} "
                f"({st['slab_rows_unique']}/{st['slab_rows_indexed']} rows)"
            )
        lines.append(line)
        touches = st.get("shard_touches")
        if touches:
            lines.append(
                "shard touches: "
                + " ".join(f"s{i}:{t}" for i, t in enumerate(touches))
            )
    fair = report.get("fairness") or {}
    if fair:
        lines.append(
            f"fairness (top-{fair.get('tracked', 0)} sketch): gini "
            f"{fair.get('gini', 0.0):.3f}  max share "
            f"{100.0 * fair.get('max_share', 0.0):.2f}%  top clients "
            + ", ".join(
                f"{c}×{n}" for c, n in (fair.get("top_clients") or [])
            )
        )
    return "\n".join(lines)


def strip_timing_keys(obj):
    """Recursively drop every ``*_ms`` key — the parity tests' helper
    for comparing population records across engines (wall-clock is the
    ONE record family allowed to differ; counts must be identical)."""
    if isinstance(obj, dict):
        return {
            k: strip_timing_keys(v) for k, v in obj.items()
            if not (isinstance(k, str) and k.endswith("_ms"))
        }
    if isinstance(obj, list):
        return [strip_timing_keys(v) for v in obj]
    return obj
