"""Determinism flight recorder (``run.obs.digest``, obs/digest.py).

The codebase's determinism contracts — bitwise resume replay,
sharded ≡ sequential engine parity, seed-pure cohort/churn schedules —
exist as test pins; this module makes them a *monitored* invariant at
runtime and a *bisectable* event after the fact. At each digest
boundary the driver computes a cheap, canonical, dtype/shape-tagged
64-bit digest over the fetched state and emits one ``round_digest``
JSONL record per boundary:

- ``params`` / ``params_leaves`` — the global params pytree, rolled up
  and per TOP-LEVEL leaf (module name), so a divergence localizes to
  the layer that moved;
- ``opt`` — the server optimizer state;
- ``ledger`` — the ledger/pager hot set (dense or paged rows, cold
  spill, slot maps, the active sampler snapshot/sketch);
- ``schedule`` — the realized cohort schedule + failure counts for
  every round since the previous boundary;
- ``wire`` — the per-round analytic wire-byte counters over the same
  window (empty when ``run.obs.counters`` is off);
- ``rng`` — the RNG inputs (run seed, round, sampler snapshot round).

Records chain ``prev`` → ``self`` with
``self = H(prev ‖ round ‖ components)``, so a truncated or tampered
log is self-evident: every record's ``self`` is recomputable from its
own fields, and every record's ``prev`` must equal its predecessor's
``self``. The chain head rides the checkpoint (``digest_head``) and
resume verifies it against the log before training continues.

Hashing is ``hashlib.blake2b(digest_size=8)`` — a stdlib, C-speed
64-bit digest in the xxhash cost class (BLAKE2's keyed/tree features
unused; we need speed + stability, not cryptographic strength).
Arrays are tagged with ``dtype.str`` + shape before their contiguous
bytes, so an f32/bf16 cast or a reshape can never collide. Digests
are a pure function of the fetched state: engine-invariant wherever
the engines are bitwise (everything but wall-clock), and digest-on
runs are bitwise-identical to digest-off runs on the same seed
(test-pinned) — the recorder only ever reads.

Pure-host consumers (no backend init):

- ``colearn diff <run_a> <run_b>`` aligns two digest streams,
  verifies each chain, and localizes the FIRST divergent round +
  component (params leaf / opt / ledger / schedule / wire / rng) with
  a per-leaf drill-down; exit 1 on divergence or a broken chain.
- ``colearn replay <run> --round r`` re-executes exactly one round
  from the nearest checkpoint ≤ the record's window start and
  verifies the recomputed digest against the logged one.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# 64-bit hex digests; the genesis "prev" of a fresh chain
HEX_WIDTH = 16
GENESIS = "0" * HEX_WIDTH

# component priority when NAMING a divergence (the ISSUE's order); all
# diverged components are still listed in the report
COMPONENT_ORDER = ("params", "opt", "ledger", "schedule", "wire", "rng")

# state keys that make up the ``ledger`` component: the ledger/pager
# hot set plus the sampler's active snapshot/sketch (everything the
# selection path reads that rides the checkpoint)
LEDGER_STATE_KEYS = (
    "ledger", "ledger_cold", "ledger_slots", "ledger_slot_used",
    "ledger_snapshot", "ledger_snapshot_round",
    "ledger_sketch_ids", "ledger_sketch_stats",
)


class DigestResumeError(RuntimeError):
    """Resume-time chain-head verification failed under
    ``run.obs.digest.strict`` (the ``colearn fit --strict-digest``
    escalation of the logged ``digest_resume`` warning)."""


def _h(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=8).hexdigest()


def _canon(obj: Any) -> Any:
    """Canonicalize plain data for hashing: numpy scalars → python,
    numpy arrays → nested lists, dict keys → str."""
    if isinstance(obj, dict):
        return {str(k): _canon(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_canon(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    return obj


def json_digest(obj: Any) -> str:
    """Canonical-JSON digest of plain (non-array) data."""
    payload = json.dumps(_canon(obj), sort_keys=True, separators=(",", ":"))
    return _h(payload.encode("utf-8"))


def array_digest(a: Any) -> str:
    """Dtype/shape-tagged digest of one array: ``dtype.str`` + shape
    prefix the contiguous bytes, so a cast or reshape never collides
    with the original. Python scalars hash through a 0-d array of
    their canonical dtype."""
    arr = np.asarray(a)
    tag = f"{arr.dtype.str}:{arr.shape}:".encode("ascii")
    return _h(tag + np.ascontiguousarray(arr).tobytes())


def _flatten_with_path(tree: Any, prefix: str = "") -> List[Tuple[str, Any]]:
    """Deterministic (path, leaf) flattening: dict keys sorted, tuples/
    lists positional — stable across pytree registry details (flax
    FrozenDict vs dict) and python versions."""
    if isinstance(tree, dict) or hasattr(tree, "items"):
        out: List[Tuple[str, Any]] = []
        for k in sorted(tree.keys(), key=str):
            out.extend(_flatten_with_path(tree[k], f"{prefix}/{k}"))
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for i, v in enumerate(tree):
            out.extend(_flatten_with_path(v, f"{prefix}/{i}"))
        return out
    if hasattr(tree, "_fields"):  # NamedTuple (optax states)
        out = []
        for name in tree._fields:
            out.extend(_flatten_with_path(getattr(tree, name), f"{prefix}/{name}"))
        return out
    if tree is None:
        return []
    return [(prefix or "/", tree)]


def tree_digest(tree: Any) -> str:
    """Rolled-up digest of a pytree: each leaf's path + array digest
    folded into one running hash, in canonical path order."""
    h = hashlib.blake2b(digest_size=8)
    for path, leaf in _flatten_with_path(tree):
        h.update(path.encode("utf-8"))
        h.update(array_digest(leaf).encode("ascii"))
    return h.hexdigest()


def params_digests(params: Any) -> Tuple[str, Dict[str, str]]:
    """(rollup, {top_level_leaf: digest}) for the params pytree. The
    per-leaf map keys are the params dict's TOP-LEVEL module names —
    the drill-down ``colearn diff`` localizes a divergence to."""
    if isinstance(params, dict) or hasattr(params, "items"):
        leaves = {
            str(k): tree_digest(params[k])
            for k in sorted(params.keys(), key=str)
        }
    else:
        leaves = {"params": tree_digest(params)}
    h = hashlib.blake2b(digest_size=8)
    for k in sorted(leaves):
        h.update(k.encode("utf-8"))
        h.update(leaves[k].encode("ascii"))
    return h.hexdigest(), leaves


class RoundWindow:
    """Host-side fold of per-round schedule/wire observations between
    digest boundaries. The driver observes every round exactly once
    (at flush, in round order); ``drain`` consumes the window up to a
    boundary, so the digest stream is invariant to flush cadence and
    ``run.fuse_rounds``."""

    def __init__(self) -> None:
        self._rounds: Dict[int, Dict[str, Any]] = {}

    def observe(self, round_1b: int,
                cohort: Optional[np.ndarray],
                comm: Optional[Dict[str, Any]],
                fail: Optional[Dict[str, Any]]) -> None:
        self._rounds[int(round_1b)] = {
            "cohort": (
                None if cohort is None
                else np.asarray(cohort).astype(np.int64, copy=False)
            ),
            "comm": dict(comm) if comm else {},
            "fail": dict(fail) if fail else {},
        }

    def drain(self, upto_round: int) -> Tuple[str, str]:
        """Consume rounds ≤ ``upto_round``; returns the window's
        (schedule, wire) component digests."""
        taken = sorted(r for r in self._rounds if r <= upto_round)
        sched = {}
        wire = {}
        for r in taken:
            entry = self._rounds.pop(r)
            cohort = entry["cohort"]
            sched[str(r)] = {
                "cohort": [] if cohort is None else cohort.tolist(),
                "fail": entry["fail"],
            }
            wire[str(r)] = entry["comm"]
        return json_digest(sched), json_digest(wire)


def state_components(params: Any, opt_state: Any,
                     ledger_items: Dict[str, Any],
                     schedule_digest: str, wire_digest: str,
                     rng_inputs: Dict[str, int]) -> Dict[str, Any]:
    """The six digest components over already-fetched (host) state."""
    rollup, leaves = params_digests(params)
    return {
        "params": rollup,
        "params_leaves": leaves,
        "opt": tree_digest(opt_state),
        "ledger": tree_digest(
            {k: ledger_items[k] for k in sorted(ledger_items)}
        ),
        "schedule": schedule_digest,
        "wire": wire_digest,
        "rng": json_digest(rng_inputs),
    }


def chain_digest(prev: str, round_1b: int,
                 components: Dict[str, Any]) -> str:
    """``self = H(prev ‖ round ‖ components)`` — the hash-chain link.
    Recomputable from a record's own fields, which is what makes
    tampering self-evident."""
    payload = {
        "prev": prev, "round": int(round_1b),
        **{k: components[k] for k in COMPONENT_ORDER},
        "params_leaves": components["params_leaves"],
    }
    return json_digest(payload)


def components_from_record(record: Dict[str, Any]) -> Dict[str, Any]:
    comp = {k: record.get(k, "") for k in COMPONENT_ORDER}
    comp["params_leaves"] = record.get("params_leaves", {})
    return comp


# ---- checkpoint head packing ---------------------------------------------


def head_pack(self_hex: str, round_1b: int) -> np.ndarray:
    """Pack the chain head into the ``digest_head`` checkpoint array:
    uint32 ``[hash_lo, hash_hi, round]`` (all-zero = genesis). Always
    present in the state template so digest-on/off checkpoints stay
    template-compatible."""
    v = int(self_hex, 16) if round_1b else 0
    return np.array(
        [v & 0xFFFFFFFF, (v >> 32) & 0xFFFFFFFF, int(round_1b)],
        dtype=np.uint32,
    )


def head_unpack(head: Any) -> Tuple[str, int]:
    """(self_hex, round) from a ``digest_head`` array; genesis when the
    round slot is 0."""
    arr = np.asarray(head).astype(np.uint64).reshape(-1)
    round_1b = int(arr[2])
    if round_1b == 0:
        return GENESIS, 0
    v = int(arr[0]) | (int(arr[1]) << 32)
    return f"{v:016x}", round_1b


# ---- pure-host stream consumers ------------------------------------------


def digest_records(records: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The run's digest stream: ``round_digest`` records, LAST-wins per
    round (a crashed-then-retried attempt re-emits boundaries past its
    restore point; the latest attempt is the run's truth), in round
    order."""
    by_round: Dict[int, Dict[str, Any]] = {}
    for rec in records:
        if rec.get("event") == "round_digest":
            by_round[int(rec["round"])] = rec
    return [by_round[r] for r in sorted(by_round)]


def verify_chain(records: Sequence[Dict[str, Any]]) -> Tuple[bool, List[str]]:
    """Verify a digest stream's hash chain: every record's ``self``
    must recompute from its own fields, and every record's ``prev``
    must equal its predecessor's ``self`` (genesis for the first).
    A *truncated* log still verifies (a prefix of a valid chain is a
    valid chain) — truncation is caught by the checkpoint head on
    resume, or by the longer twin under ``colearn diff``."""
    stream = digest_records(records)
    problems: List[str] = []
    prev_hex, prev_round = GENESIS, 0
    for rec in stream:
        r = int(rec["round"])
        recomputed = chain_digest(
            rec.get("prev", ""), r, components_from_record(rec)
        )
        if recomputed != rec.get("self"):
            problems.append(
                f"round {r}: record tampered (self={rec.get('self')!r} "
                f"but fields recompute to {recomputed!r})"
            )
        if rec.get("prev") != prev_hex or int(rec.get("prev_round", -1)) != prev_round:
            problems.append(
                f"round {r}: chain broken (prev={rec.get('prev')!r}@"
                f"{rec.get('prev_round')} but predecessor is "
                f"{prev_hex!r}@{prev_round})"
            )
        prev_hex, prev_round = rec.get("self", ""), r
    return not problems, problems


def resume_head_status(records: Sequence[Dict[str, Any]], head_hex: str,
                       head_round: int) -> Tuple[bool, str]:
    """Resume-time verification of the checkpoint's chain head against
    the (about-to-be-appended-to) log: the log must contain a chain-
    valid ``round_digest`` record at ``head_round`` whose ``self``
    matches the head. A truncated log (head record missing) and a
    tampered log (chain broken at or before the head) both fail."""
    if head_round == 0:
        return True, "genesis head (no digests before this checkpoint)"
    stream = digest_records(records)
    upto = [r for r in stream if int(r["round"]) <= head_round]
    ok, problems = verify_chain(upto)
    if not ok:
        return False, problems[0]
    if not upto or int(upto[-1]["round"]) != head_round:
        last = int(upto[-1]["round"]) if upto else None
        return False, (
            f"log truncated: checkpoint head is round {head_round} but "
            f"the log's last digest at or before it is "
            f"{'missing' if last is None else f'round {last}'}"
        )
    if upto[-1].get("self") != head_hex:
        return False, (
            f"head mismatch at round {head_round}: checkpoint carries "
            f"{head_hex!r} but the log records {upto[-1].get('self')!r}"
        )
    return True, f"chain verified through round {head_round}"


def _divergent_components(ra: Dict[str, Any],
                          rb: Dict[str, Any]) -> List[str]:
    return [
        c for c in COMPONENT_ORDER if ra.get(c, "") != rb.get(c, "")
    ]


def _leaf_diff(ra: Dict[str, Any], rb: Dict[str, Any]) -> List[str]:
    la, lb = ra.get("params_leaves", {}), rb.get("params_leaves", {})
    keys = sorted(set(la) | set(lb))
    return [k for k in keys if la.get(k) != lb.get(k)]


def diff_streams(records_a: Sequence[Dict[str, Any]],
                 records_b: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Align two digest streams by round and localize the FIRST
    divergent round + component. Alignment is over the round
    intersection, so runs at different digest cadences still compare
    at their common boundaries. Returns a report dict whose ``status``
    drives the CLI exit code: ``match`` (0), ``diverged`` /
    ``chain_broken`` (1), ``no_overlap`` (2)."""
    stream_a, stream_b = digest_records(records_a), digest_records(records_b)
    ok_a, problems_a = verify_chain(records_a)
    ok_b, problems_b = verify_chain(records_b)
    report: Dict[str, Any] = {
        "rounds_a": len(stream_a), "rounds_b": len(stream_b),
        "chain_a_ok": ok_a, "chain_b_ok": ok_b,
        "chain_a_problems": problems_a, "chain_b_problems": problems_b,
    }
    if not (ok_a and ok_b):
        report["status"] = "chain_broken"
        return report
    by_a = {int(r["round"]): r for r in stream_a}
    by_b = {int(r["round"]): r for r in stream_b}
    common = sorted(set(by_a) & set(by_b))
    report["common_rounds"] = len(common)
    if not common:
        report["status"] = "no_overlap"
        return report
    for r in common:
        ra, rb = by_a[r], by_b[r]
        if ra.get("self") == rb.get("self"):
            continue
        diverged = _divergent_components(ra, rb)
        # chains verified + selfs differ ⇒ some field differs; an
        # upstream prev-divergence alone shows as equal components
        # with different prev links (the earlier round was not common)
        primary = diverged[0] if diverged else "prev"
        report.update({
            "status": "diverged",
            "first_divergent_round": r,
            "component": primary,
            "components": diverged,
            "params_leaves": (
                _leaf_diff(ra, rb) if "params" in diverged else []
            ),
        })
        return report
    # every common boundary matches; differing tails are continuation,
    # not divergence (a resumed twin that ran further, or an earlier
    # snapshot of the same run)
    report["status"] = "match"
    report["last_common_round"] = common[-1]
    return report


def format_diff(report: Dict[str, Any], name_a: str, name_b: str) -> str:
    lines = [
        f"digest diff: {name_a} vs {name_b}",
        f"  digest rounds: {report.get('rounds_a', 0)} vs "
        f"{report.get('rounds_b', 0)}"
        + (f" ({report.get('common_rounds', 0)} common)"
           if "common_rounds" in report else ""),
        f"  chain: {'OK' if report.get('chain_a_ok') else 'BROKEN'} vs "
        f"{'OK' if report.get('chain_b_ok') else 'BROKEN'}",
    ]
    for side, key in ((name_a, "chain_a_problems"),
                      (name_b, "chain_b_problems")):
        for p in report.get(key, []):
            lines.append(f"    {side}: {p}")
    status = report.get("status")
    if status == "no_overlap":
        lines.append(
            "  no common digest rounds — different digest cadences or "
            "disjoint round ranges; nothing to compare"
        )
    elif status == "diverged":
        r = report["first_divergent_round"]
        comps = ", ".join(report.get("components", []))
        lines.append(
            f"  FIRST DIVERGENCE at round {r}: component "
            f"{report['component']} (diverged: {comps})"
        )
        for leaf in report.get("params_leaves", []):
            lines.append(f"    params leaf diverged: {leaf}")
    elif status == "match":
        lines.append(
            f"  streams identical through round "
            f"{report.get('last_common_round')} — no divergence"
        )
    return "\n".join(lines)


def watch_digest_status(records: Sequence[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """One-line digest-chain status for ``colearn watch``: last digest
    round, chain OK/broken, and any failed resume verification. None
    when the run logs no digests (recorder off)."""
    stream = digest_records(records)
    resume_fail = None
    for rec in records:
        if rec.get("event") == "digest_resume" and not rec.get("ok", True):
            resume_fail = {
                "round": int(rec.get("round", 0)),
                "detail": rec.get("detail", ""),
            }
    if not stream and resume_fail is None:
        return None
    ok, problems = verify_chain(stream)
    return {
        "last_round": int(stream[-1]["round"]) if stream else 0,
        "chain_ok": ok,
        "problems": problems[:1],
        "resume_fail": resume_fail,
    }
