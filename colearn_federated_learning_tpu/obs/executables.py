"""Compiled-program observatory (``run.obs.executables``,
obs/executables.py): the executable registry that makes XLA's own view
of every compiled program — FLOPs, HBM bytes, donation, retraces — a
first-class run artifact.

The engines' jit sites are wrapped with :func:`instrument`, which is a
no-op passthrough until a registry is installed (the driver installs
one per fit when ``run.obs.executables`` is on). With a registry
active, each wrapped call routes through the registry's AOT executable
cache: the first call for a given (name, avals, shardings, statics)
fingerprint lowers and compiles the program explicitly
(``fn.lower(*args).compile()``) — the SAME lowering ``jax.jit`` would
produce, so execution is bitwise-identical — and harvests, per
compiled program:

* ``cost_analysis()`` FLOPs / bytes-accessed (XLA's cost model of the
  optimized HLO — the measured half of the ``colearn mfu`` drift gate),
* ``memory_analysis()`` argument / output / temp / generated-code
  bytes (the predicted HBM working set; donation-aliased bytes are
  counted once),
* the donation map (which inputs the program consumes in place),
* a stable hex fingerprint (name + per-leaf aval/sharding descriptors
  + statics + backend), and the compile wall-ms,

queued as ``executable_compiled`` JSONL records the driver logs at
flush boundaries. Recompiles of an already-seen program name diff the
new fingerprint's per-argument descriptors against the cached ones and
queue a ``retrace`` record naming exactly which argument changed
shape/dtype/sharding. A live HBM ledger tracks the high-water mark
over the programs called in each flush window (``hbm_watermark``
records + run peak in ``run_summary``).

Degradation contract: any failure anywhere in the registry path —
lowering, compiling, analysis harvesting, or calling the cached
executable — permanently falls back to the plain jitted call for that
program name and records partial (null-field) data. The registry must
never change what a fit computes or whether it completes (budget
aborts below are the one deliberate exception).

OOM preflight: with ``preflight=True`` the registry lowers and
compiles but NEVER executes — wrapped calls return abstract
``jax.ShapeDtypeStruct`` outputs — so ``colearn preflight`` can walk
one round of the driver's dispatch path and report the predicted peak
HBM (naming the dominant buffers) without binding output or temp
buffers. With ``run.obs.hbm_budget_mb`` set, a newly compiled
program whose predicted peak exceeds the budget raises
:class:`HbmBudgetError` BEFORE the program executes — the driver's
pre-fit/over-budget abort (not retried by ``run.max_retries``).
"""

from __future__ import annotations

import contextlib
import functools
import hashlib
import inspect
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

__all__ = [
    "ExecutableRegistry",
    "HbmBudgetError",
    "current",
    "device_hbm_capacity",
    "install",
    "instrument",
    "uninstall",
]


def device_hbm_capacity() -> int:
    """``bytes_limit`` of device 0's allocator — the capacity the
    over-capacity warning compares against. 0 when the backend doesn't
    report memory stats (CPU)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        return int((stats or {}).get("bytes_limit", 0))
    except Exception:
        return 0

# the process-global active registry (installed by the driver per fit,
# or by `colearn preflight` around its dry round). A module-level slot
# — not a contextvar — on purpose: the engines' wrappers are built once
# at factory time and must see a registry installed AFTER they were
# created.
_ACTIVE: Optional["ExecutableRegistry"] = None

# retrace records cap the per-argument diff list: a resharded state
# pytree would otherwise name hundreds of leaves for one cause
_MAX_CHANGED = 8
# dominant-buffer lists in preflight reports / budget errors
_TOP_BUFFERS = 3


class HbmBudgetError(RuntimeError):
    """A newly compiled program's predicted peak HBM exceeds
    ``run.obs.hbm_budget_mb``. Raised BEFORE the program executes;
    deliberately not retried by ``run.max_retries`` (recompiling the
    same program predicts the same peak)."""


def install(registry: "ExecutableRegistry") -> None:
    global _ACTIVE
    _ACTIVE = registry


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def current() -> Optional["ExecutableRegistry"]:
    return _ACTIVE


def instrument(name: str, fn: Callable, *,
               static_argnums: Tuple[int, ...] = (),
               rounds_per_call: int = 1) -> Callable:
    """Wrap a jitted callable so an installed registry intercepts its
    lowerings. Without a registry (or under tracing — e.g. the sharded
    round_fn inlined inside the device-plane program) the wrapper is a
    plain passthrough to ``fn``. ``rounds_per_call`` declares how many
    federated rounds one call advances (``run.fuse_rounds`` for the
    fused programs) so per-round FLOP joins normalize correctly."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        reg = _ACTIVE
        if reg is None:
            return fn(*args, **kwargs)
        return reg.call(name, fn, args, kwargs,
                        static_argnums=static_argnums,
                        rounds_per_call=rounds_per_call)

    wrapper.__wrapped__ = fn
    return wrapper


# ---------------------------------------------------------------------------
# fingerprinting


def _leaf_desc(x) -> tuple:
    """Hashable per-leaf descriptor with exactly jit's cache-key
    granularity: aval (shape/dtype/weak_type) + sharding for arrays,
    dtype-kind only for python scalars (jit keys them by weak dtype,
    not value)."""
    aval = getattr(x, "aval", None)
    if aval is not None:
        return ("a", tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)),
                getattr(x, "sharding", None))
    if isinstance(x, jax.ShapeDtypeStruct):
        return ("s", tuple(x.shape), str(x.dtype),
                getattr(x, "sharding", None))
    if isinstance(x, (np.ndarray, np.generic)):
        return ("n", tuple(x.shape), str(x.dtype))
    if isinstance(x, (bool, int, float, complex)):
        return ("p", type(x).__name__)
    # non-array leaf the jit would treat structurally — repr-keyed
    return ("o", repr(x)[:120])


def _leaf_is_tracer(x) -> bool:
    try:
        return isinstance(x, jax.core.Tracer)
    except Exception:
        return False


def _cache_key(args, kwargs, static_argnums):
    """(statics, treedef, leaf descriptors) — hashable, computed on
    every registry call, so it must stay allocation-light. Returns
    (key, leaves) or (None, None) when a leaf is a tracer (the wrapper
    is being inlined inside an outer program)."""
    statics = tuple(
        repr(args[i]) if i < len(args) else None for i in static_argnums
    )
    dyn = tuple(
        a for i, a in enumerate(args) if i not in static_argnums
    )
    leaves, treedef = jax.tree_util.tree_flatten((dyn, kwargs))
    for leaf in leaves:
        if _leaf_is_tracer(leaf):
            return None, None
    return (statics, treedef, tuple(_leaf_desc(x) for x in leaves)), leaves


def _arg_paths(fn, args, kwargs, static_argnums):
    """Per-leaf (path, {shape, dtype, sharding}) descriptors with
    signature-derived names — the retrace diff and dominant-buffer
    naming read these. Best-effort: positional ``arg<i>`` names when
    the signature can't be bound."""
    names: List[Tuple[str, Any]] = []
    try:
        sig = inspect.signature(fn)
        bound = sig.bind(*args, **kwargs)
        items = list(bound.arguments.items())
    except Exception:
        items = [(f"arg{i}", a) for i, a in enumerate(args)]
        items += sorted(kwargs.items())
    static_names = set()
    try:
        params = list(inspect.signature(fn).parameters)
        static_names = {params[i] for i in static_argnums
                        if i < len(params)}
    except Exception:
        static_names = {f"arg{i}" for i in static_argnums}
    out: Dict[str, Dict[str, Any]] = {}
    for pname, val in items:
        if pname in static_names:
            out[pname] = {"shape": None, "dtype": None,
                          "sharding": None, "static": repr(val)[:120]}
            continue
        try:
            flat = jax.tree_util.tree_flatten_with_path(val)[0]
        except Exception:
            continue
        for path, leaf in flat:
            key = pname + jax.tree_util.keystr(path)
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            sharding = getattr(leaf, "sharding", None)
            out[key] = {
                "shape": None if shape is None else list(shape),
                "dtype": None if dtype is None else str(dtype),
                "sharding": None if sharding is None else repr(sharding),
            }
    _ = names
    return out


def _fingerprint_hex(name: str, key) -> str:
    """Stable hex fingerprint: name + statics + tree structure + leaf
    descriptors + backend/compile-option bits. Deterministic across
    runs of the same config (test-pinned)."""
    statics, treedef, descs = key
    h = hashlib.sha256()
    h.update(name.encode())
    h.update(repr(statics).encode())
    h.update(str(treedef).encode())
    for d in descs:
        h.update(repr(d).encode())
    h.update(jax.default_backend().encode())
    h.update(str(jax.device_count()).encode())
    h.update(str(bool(jax.config.jax_enable_x64)).encode())
    return h.hexdigest()[:16]


def _leaf_bytes(desc: Dict[str, Any]) -> int:
    if not desc.get("shape") and desc.get("shape") != []:
        return 0
    try:
        n = 1
        for d in desc["shape"]:
            n *= int(d)
        return n * np.dtype(desc["dtype"]).itemsize
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# the registry


class ExecutableRegistry:
    """Per-fit AOT executable cache + record queue. See module
    docstring for the full contract. Not thread-safe by design: the
    driver's dispatch loop is single-threaded."""

    def __init__(self, *, preflight: bool = False,
                 hbm_budget_bytes: int = 0,
                 device_capacity_bytes: int = 0,
                 tracer=None, backend: Optional[str] = None):
        self.preflight = preflight
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        self.device_capacity_bytes = int(device_capacity_bytes)
        self.tracer = tracer
        self.backend = backend or jax.default_backend()
        self.round = 0  # the driver advances this before each dispatch
        # fingerprint-key -> {"compiled", "fingerprint", "name",
        #                     "abstract_out", "stats"}
        self._cache: Dict[Any, Dict[str, Any]] = {}
        # name -> {"fingerprint", "paths", "compiles", "peak_bytes"}
        self._programs: Dict[str, Dict[str, Any]] = {}
        # names whose AOT path failed once: plain jit calls from then on
        self._aot_off: set = set()
        self._records: List[Dict[str, Any]] = []
        # flush-window program names (for the hbm_watermark record)
        self._window: set = set()
        self.peak_bytes = 0
        self.peak_program: Optional[str] = None
        self.total_compiles = 0
        self.total_compile_ms = 0.0

    # -- spans ----------------------------------------------------------
    def _span(self, label: str):
        if self.tracer is None:
            return contextlib.nullcontext()
        try:
            return self.tracer.span(label)
        except Exception:
            return contextlib.nullcontext()

    # -- the wrapped-call entry point -----------------------------------
    def call(self, name: str, fn: Callable, args: tuple, kwargs: dict,
             *, static_argnums: Tuple[int, ...] = (),
             rounds_per_call: int = 1):
        if name in self._aot_off and not self.preflight:
            return fn(*args, **kwargs)
        try:
            key, _ = _cache_key(args, kwargs, static_argnums)
        except Exception:
            key = None
        if key is None:
            # tracer leaves (inlined inside an outer program) or an
            # unfingerprintable input: stay out of the way
            return fn(*args, **kwargs)
        hit = self._cache.get(key)
        if hit is not None:
            self._window.add(name)
            if self.preflight:
                return hit["abstract_out"]
            compiled = hit["compiled"]
            if compiled is None:
                return fn(*args, **kwargs)
            try:
                return compiled(*args, **kwargs)
            except Exception as e:  # pragma: no cover - safety net
                # fingerprint collision or input/layout drift the key
                # missed: disable AOT for this name, warn, re-dispatch
                # through jit (inputs are intact — the AOT call
                # validates before executing)
                self._aot_off.add(name)
                self._records.append({
                    "event": "warning",
                    "warning": "executable_aot_fallback",
                    "detail": f"{name}: {type(e).__name__}: {e}"[:300],
                    "round": int(self.round),
                })
                return fn(*args, **kwargs)
        return self._compile_and_call(name, fn, args, kwargs, key,
                                      static_argnums, rounds_per_call)

    # -- slow path: first sight of a fingerprint ------------------------
    def _compile_and_call(self, name, fn, args, kwargs, key,
                          static_argnums, rounds_per_call):
        span = "obs.preflight" if self.preflight else "obs.executables"
        with self._span(span):
            fingerprint = _fingerprint_hex(name, key)
            t0 = time.perf_counter()
            try:
                lowered = fn.lower(*args, **kwargs)
                compiled = lowered.compile()
            except Exception as e:
                self._aot_off.add(name)
                compile_ms = (time.perf_counter() - t0) * 1e3
                self._emit_compiled(name, fingerprint, None, compile_ms,
                                    rounds_per_call)
                self._records.append({
                    "event": "warning",
                    "warning": "executable_lower_failed",
                    "detail": f"{name}: {type(e).__name__}: {e}"[:300],
                    "round": int(self.round),
                })
                if self.preflight:
                    raise
                return fn(*args, **kwargs)
            compile_ms = (time.perf_counter() - t0) * 1e3
            stats = self._harvest(lowered, compiled)
            paths = self._paths_or_none(fn, args, kwargs, static_argnums)
            prev = self._programs.get(name)
            if prev is not None and prev["fingerprint"] != fingerprint:
                self._emit_retrace(name, prev, fingerprint, paths)
            self._programs[name] = {
                "fingerprint": fingerprint,
                "paths": paths,
                "compiles": (prev["compiles"] + 1) if prev else 1,
                "peak_bytes": stats.get("peak_bytes"),
                "rounds_per_call": int(rounds_per_call),
                "stats": stats,
            }
            abstract_out = self._abstract_out(lowered)
            self._cache[key] = {
                "compiled": compiled,
                "fingerprint": fingerprint,
                "name": name,
                "abstract_out": abstract_out,
                "stats": stats,
            }
            self._window.add(name)
            self.total_compiles += 1
            self.total_compile_ms += compile_ms
            peak = stats.get("peak_bytes")
            if peak is not None and peak > self.peak_bytes:
                self.peak_bytes = int(peak)
                self.peak_program = name
            self._emit_compiled(name, fingerprint, stats, compile_ms,
                                rounds_per_call)
            self._check_budget(name, stats, paths)
        if self.preflight:
            return abstract_out
        try:
            return compiled(*args, **kwargs)
        except HbmBudgetError:
            raise
        except Exception as e:
            self._aot_off.add(name)
            self._records.append({
                "event": "warning",
                "warning": "executable_aot_fallback",
                "detail": f"{name}: {type(e).__name__}: {e}"[:300],
                "round": int(self.round),
            })
            return fn(*args, **kwargs)

    # -- harvesting ------------------------------------------------------
    @staticmethod
    def _paths_or_none(fn, args, kwargs, static_argnums):
        try:
            target = getattr(fn, "__wrapped__", fn)
            return _arg_paths(target, args, kwargs, static_argnums)
        except Exception:
            return None

    @staticmethod
    def _harvest(lowered, compiled) -> Dict[str, Any]:
        """Pull cost/memory analysis off the compiled executable.
        Availability varies by backend and jax version — every field
        degrades to None independently, never raises (test-pinned)."""
        stats: Dict[str, Any] = {
            "flops": None, "bytes_accessed": None,
            "argument_bytes": None, "output_bytes": None,
            "temp_bytes": None, "generated_code_bytes": None,
            "alias_bytes": None, "peak_bytes": None,
            "donated_args": None,
        }
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if ca:
                flops = ca.get("flops")
                ba = ca.get("bytes accessed")
                stats["flops"] = None if flops is None else float(flops)
                stats["bytes_accessed"] = None if ba is None else float(ba)
        except Exception:
            pass
        try:
            mem = compiled.memory_analysis()
            if mem is not None:
                stats["argument_bytes"] = int(mem.argument_size_in_bytes)
                stats["output_bytes"] = int(mem.output_size_in_bytes)
                stats["temp_bytes"] = int(mem.temp_size_in_bytes)
                stats["generated_code_bytes"] = int(
                    mem.generated_code_size_in_bytes
                )
                stats["alias_bytes"] = int(mem.alias_size_in_bytes)
                # donation-aliased output bytes reuse their argument's
                # buffer — count the resident set once
                stats["peak_bytes"] = (
                    stats["argument_bytes"] + stats["output_bytes"]
                    - stats["alias_bytes"] + stats["temp_bytes"]
                    + stats["generated_code_bytes"]
                )
        except Exception:
            pass
        try:
            flat = jax.tree_util.tree_flatten(lowered.args_info)[0]
            stats["donated_args"] = sum(
                1 for a in flat if getattr(a, "donated", False)
            )
        except Exception:
            pass
        return stats

    @staticmethod
    def _abstract_out(lowered):
        """ShapeDtypeStruct pytree mirroring the program's outputs —
        what preflight-mode calls return instead of executing."""
        try:
            return jax.tree.map(
                lambda o: jax.ShapeDtypeStruct(o.shape, o.dtype),
                lowered.out_info,
            )
        except Exception:
            return None

    # -- record construction --------------------------------------------
    def _emit_compiled(self, name, fingerprint, stats, compile_ms,
                       rounds_per_call):
        stats = stats or {}
        self._records.append({
            "event": "executable_compiled",
            "round": int(self.round),
            "name": name,
            "fingerprint": fingerprint,
            "compile_ms": round(float(compile_ms), 3),
            "flops": stats.get("flops"),
            "bytes_accessed": stats.get("bytes_accessed"),
            "argument_bytes": stats.get("argument_bytes"),
            "output_bytes": stats.get("output_bytes"),
            "temp_bytes": stats.get("temp_bytes"),
            "generated_code_bytes": stats.get("generated_code_bytes"),
            "peak_bytes": stats.get("peak_bytes"),
            "donated_args": stats.get("donated_args"),
            "rounds_per_call": int(rounds_per_call),
            "backend": self.backend,
            "preflight": bool(self.preflight),
        })

    def _emit_retrace(self, name, prev, fingerprint, paths):
        changed = []
        old = prev.get("paths") or {}
        new = paths or {}
        for arg in sorted(set(old) | set(new)):
            if old.get(arg) != new.get(arg):
                changed.append({
                    "arg": arg,
                    "before": old.get(arg),
                    "after": new.get(arg),
                })
        self._records.append({
            "event": "retrace",
            "round": int(self.round),
            "name": name,
            "fingerprint": fingerprint,
            "prev_fingerprint": prev["fingerprint"],
            "n_changed": len(changed),
            "changed": changed[:_MAX_CHANGED],
        })

    def _check_budget(self, name, stats, paths):
        peak = stats.get("peak_bytes")
        if peak is None:
            return
        cap = self.device_capacity_bytes
        if cap and peak > cap and not self.hbm_budget_bytes:
            self._records.append({
                "event": "warning",
                "warning": "hbm_over_capacity",
                "detail": (
                    f"{name}: predicted peak "
                    f"{peak / 2**20:.1f} MiB exceeds device capacity "
                    f"{cap / 2**20:.1f} MiB"
                ),
                "round": int(self.round),
            })
        budget = self.hbm_budget_bytes
        if budget and peak > budget:
            dom = self.dominant_buffers(name)
            dom_s = ", ".join(
                f"{a} ({b / 2**20:.1f} MiB)" for a, b in dom
            ) or "n/a"
            raise HbmBudgetError(
                f"program {name!r}: predicted peak HBM "
                f"{peak / 2**20:.1f} MiB exceeds run.obs.hbm_budget_mb="
                f"{budget // 2**20} ({budget / 2**20:.1f} MiB); "
                f"dominant buffers: {dom_s}"
            )

    # -- reporting -------------------------------------------------------
    def dominant_buffers(self, name: str) -> List[Tuple[str, int]]:
        """Largest input leaves of a program by bytes (+ the temp
        scratch as a pseudo-buffer when it dominates)."""
        entry = self._programs.get(name)
        if entry is None:
            return []
        paths = entry.get("paths") or {}
        sized = sorted(
            ((arg, _leaf_bytes(d)) for arg, d in paths.items()),
            key=lambda t: -t[1],
        )
        out = [(a, b) for a, b in sized[:_TOP_BUFFERS] if b > 0]
        stats = entry.get("stats") or {}
        temp = stats.get("temp_bytes")
        if temp and (not out or temp > out[-1][1]):
            out.append(("(temp scratch)", int(temp)))
            out.sort(key=lambda t: -t[1])
            out = out[:_TOP_BUFFERS]
        return out

    def drain_records(self) -> List[Dict[str, Any]]:
        recs, self._records = self._records, []
        return recs

    def watermark(self, last_round: int) -> Optional[Dict[str, Any]]:
        """One flush window's HBM high-water record: the max predicted
        peak over the programs called since the previous watermark.
        None when nothing ran (or nothing had memory analysis)."""
        names, self._window = self._window, set()
        best: Tuple[int, Optional[str]] = (0, None)
        for n in names:
            entry = self._programs.get(n)
            peak = (entry or {}).get("peak_bytes")
            if peak is not None and peak > best[0]:
                best = (int(peak), n)
        if best[1] is None:
            return None
        stats = self._programs[best[1]].get("stats") or {}
        arg_b = stats.get("argument_bytes") or 0
        out_b = stats.get("output_bytes") or 0
        alias_b = stats.get("alias_bytes") or 0
        return {
            "event": "hbm_watermark",
            "round": int(last_round),
            "watermark_bytes": best[0],
            "program": best[1],
            "resident_bytes": int(arg_b + out_b - alias_b),
            "temp_bytes": stats.get("temp_bytes"),
            "programs": len(names),
            "peak_bytes": int(self.peak_bytes),
        }

    def measured_round_flops(self) -> Optional[Tuple[str, float]]:
        """(program, per-round flops) of the dominant compiled round
        program by XLA cost_analysis — the measured side of the
        measured-vs-analytic drift join. None when no round program
        compiled or the backend reports no cost analysis."""
        best: Optional[Tuple[str, float]] = None
        for name, entry in self._programs.items():
            if not name.startswith("round."):
                continue
            fl = (entry.get("stats") or {}).get("flops")
            if fl is None:
                continue
            per_round = float(fl) / max(1, int(entry.get("rounds_per_call") or 1))
            if best is None or per_round > best[1]:
                best = (name, per_round)
        return best

    def preflight_report(self) -> Dict[str, Any]:
        programs = []
        for name, entry in sorted(self._programs.items()):
            stats = entry.get("stats") or {}
            programs.append({
                "name": name,
                "fingerprint": entry["fingerprint"],
                "flops": stats.get("flops"),
                "argument_bytes": stats.get("argument_bytes"),
                "output_bytes": stats.get("output_bytes"),
                "temp_bytes": stats.get("temp_bytes"),
                "generated_code_bytes": stats.get("generated_code_bytes"),
                "peak_bytes": stats.get("peak_bytes"),
                "donated_args": stats.get("donated_args"),
                "dominant": [
                    {"arg": a, "bytes": b}
                    for a, b in self.dominant_buffers(name)
                ],
            })
        return {
            "backend": self.backend,
            "predicted_peak_bytes": int(self.peak_bytes),
            "predicted_peak_program": self.peak_program,
            "hbm_budget_bytes": int(self.hbm_budget_bytes),
            "device_capacity_bytes": int(self.device_capacity_bytes),
            "programs": programs,
        }


def _mib(n: Optional[int]) -> str:
    if n is None:
        return "n/a"
    return f"{n / 2**20:,.1f}"


def format_preflight_report(report: Dict[str, Any]) -> str:
    """Human table for `colearn preflight`: per-program predicted HBM
    footprint with the dominant buffers, then the peak vs the budget /
    device capacity verdict."""
    lines = [f"preflight ({report['backend']})"]
    lines.append(
        f"{'program':<22} {'peak MiB':>10} {'args MiB':>10} "
        f"{'temp MiB':>10} {'flops':>14}  dominant"
    )
    for prog in report["programs"]:
        dom = ", ".join(
            f"{d['arg']} ({_mib(d['bytes'])} MiB)" for d in prog["dominant"][:2]
        ) or "n/a"
        flops = prog.get("flops")
        lines.append(
            f"{prog['name']:<22} {_mib(prog.get('peak_bytes')):>10} "
            f"{_mib(prog.get('argument_bytes')):>10} "
            f"{_mib(prog.get('temp_bytes')):>10} "
            f"{flops if flops is None else format(int(flops), ','):>14}  {dom}"
        )
    peak = report["predicted_peak_bytes"]
    prog = report["predicted_peak_program"] or "n/a"
    lines.append(f"predicted peak: {_mib(peak)} MiB ({prog})")
    budget = report["hbm_budget_bytes"]
    cap = report["device_capacity_bytes"]
    if budget:
        verdict = "OK" if peak <= budget else "OVER BUDGET"
        lines.append(f"budget:         {_mib(budget)} MiB -> {verdict}")
    if cap:
        verdict = "OK" if peak <= cap else "OVER CAPACITY"
        lines.append(f"capacity:       {_mib(cap)} MiB -> {verdict}")
    if not budget and not cap:
        lines.append("budget:         none (set run.obs.hbm_budget_mb "
                     "to gate; CPU backend reports no capacity)")
    return "\n".join(lines)
