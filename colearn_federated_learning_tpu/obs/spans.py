"""Phase-span tracer for the round lifecycle.

Design constraints, in order:

1. **Off is free.** With ``run.obs.spans=false`` a ``span()`` call
   returns a shared no-op context manager — no clock reads, no
   allocation — so the round loop's hot path pays one attribute check.
2. **On is cheap.** An enabled span is two ``perf_counter`` reads and
   one dict update under a lock (spans fire from the fit loop AND the
   stream-prefetch worker thread). Chrome-trace event objects are only
   built when ``run.obs.trace=true``.
3. **Drain-at-flush.** The driver drains per-phase aggregates at its
   metrics-flush boundaries and logs ONE ``spans`` record per window —
   the JSONL stays one-line-per-round-scale, not one-line-per-span.

Retrace attribution: ``jax.monitoring`` fires a
``.../backend_compile_duration`` event for every XLA compilation; a
module-level listener forwards those into every live tracer, so an
unexpected mid-run retrace shows up as a ``compile`` pseudo-phase in
the same window it stalled (and as a timeline block in the trace).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence

# exported-trace size past which export() warns once: multi-GB
# trace.json files load poorly (or not at all) in Perfetto and are
# almost always an unintended artifact of a very long traced run
TRACE_SIZE_WARN_BYTES = 256 * 2**20


class _NullSpan:
    """Shared no-op span: the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

# live tracers the jax.monitoring compile listener forwards into; weak
# so finished Experiments don't accumulate across a process's lifetime
_ACTIVE: "weakref.WeakSet[Tracer]" = weakref.WeakSet()
_LISTENER_INSTALLED = False


def _on_event_duration(event, duration, **kw):
    if "backend_compile" not in event:
        return
    for tracer in list(_ACTIVE):
        tracer._note_compile(float(duration))


def _install_listener() -> None:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    _LISTENER_INSTALLED = True  # never retry a failed install per call
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_event_duration)
    except Exception:
        pass  # no jax / no monitoring API: spans still work, no retrace attribution


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_start")

    def __init__(self, tracer: "Tracer", name: str, args=None):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._start = self._tracer._clock()
        return self

    def __exit__(self, *exc):
        self._tracer._record(self._name, self._start, self._tracer._clock(),
                             self._args)
        return False


class Tracer:
    """Aggregating span tracer with optional Chrome-trace export.

    ``span(name)`` is a context manager; nesting is expressed naturally
    (a child span's interval lies inside its parent's) and survives into
    the exported trace because complete ("X") events on the same thread
    track stack in Perfetto's flame view.
    """

    def __init__(self, enabled: bool = True, trace: bool = False, clock=None,
                 max_events: int = 0, process_index: int = 0):
        self.enabled = enabled
        self.trace = trace and enabled
        # multi-process runs: the process index IS the Chrome-trace pid,
        # so each host gets its own lane group in Perfetto and
        # :meth:`export` can merge per-host fragments into one timeline
        # (os.getpid() would collide semantics across re-runs and says
        # nothing about WHICH host a lane belongs to)
        self.process_index = int(process_index)
        self._clock = clock or time.perf_counter
        self._lock = threading.Lock()
        self._agg: Dict[str, List[float]] = {}  # name -> [count, total_s, max_s]
        self._events: List[Dict[str, Any]] = []
        # cap on accumulated Chrome-trace events (run.obs.
        # trace_max_events): long runs otherwise grow trace.json without
        # bound. 0 = unlimited; past the cap events are DROPPED with one
        # warning — the per-phase aggregates keep counting everything.
        self._max_events = int(max_events)
        self._truncated = False
        self._size_warned = False
        self._t0 = self._clock()
        self._compiles = 0
        self._compile_secs = 0.0
        self._compile_max = 0.0
        if enabled:
            _install_listener()
            _ACTIVE.add(self)

    # ------------------------------------------------------------------

    def span(self, name: str, **args):
        """``args`` annotate the span (e.g. ``span("round.dispatch",
        fuse=10)``): they ride into the Chrome-trace event's ``args``
        dict so the timeline shows per-chunk attributes; the per-phase
        aggregates stay keyed by name only (one stable phase taxonomy
        regardless of attribute values)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def _record(self, name: str, start: float, end: float,
                args=None) -> None:
        dur = end - start
        with self._lock:
            agg = self._agg.get(name)
            if agg is None:
                self._agg[name] = [1, dur, dur]
            else:
                agg[0] += 1
                agg[1] += dur
                if dur > agg[2]:
                    agg[2] = dur
            if self.trace:
                event = {
                    "name": name,
                    "ph": "X",
                    "pid": self.process_index,
                    "tid": threading.get_ident() & 0xFFFF,
                    "ts": (start - self._t0) * 1e6,  # µs, run-relative
                    "dur": dur * 1e6,
                }
                if args:
                    event["args"] = args
                self._append_event(event)

    def _note_compile(self, duration: float) -> None:
        with self._lock:
            self._compiles += 1
            self._compile_secs += duration
            if duration > self._compile_max:
                self._compile_max = duration
            if self.trace:
                now = self._clock()
                self._append_event({
                    "name": "compile",
                    "ph": "X",
                    "pid": self.process_index,
                    "tid": threading.get_ident() & 0xFFFF,
                    # the monitoring hook fires at compile END; back-date
                    # the block so the timeline shows when it ran
                    "ts": max(0.0, (now - self._t0 - duration)) * 1e6,
                    "dur": duration * 1e6,
                })

    def _append_event(self, event: Dict[str, Any]) -> None:
        """Append one Chrome-trace event under the event cap (caller
        holds the lock). Warn ONCE when the cap truncates the trace."""
        if self._max_events and len(self._events) >= self._max_events:
            if not self._truncated:
                self._truncated = True
                logging.getLogger(__name__).warning(
                    "trace event cap reached (%d events): further trace "
                    "events are dropped — raise run.obs.trace_max_events "
                    "(or set 0 for unbounded) if you need the full "
                    "timeline; span aggregates are unaffected",
                    self._max_events,
                )
            return
        self._events.append(event)

    # ------------------------------------------------------------------

    def compile_stats(self) -> tuple:
        """Non-draining snapshot of the backend_compile listener's
        counters since the last :meth:`drain`: ``(count, total_secs)``.
        The driver brackets a dispatch with two snapshots to attribute
        compiles to the shape bucket that triggered them (the
        per-bucket retrace accounting of ``run.shape_buckets``)."""
        with self._lock:
            return self._compiles, self._compile_secs

    def drain(self) -> Dict[str, Dict[str, float]]:
        """Return and reset the per-phase aggregates since the last
        drain: ``{phase: {count, total_ms, max_ms}}``, with compiles
        (retraces included) reported as the ``compile`` pseudo-phase."""
        with self._lock:
            agg, self._agg = self._agg, {}
            compiles, self._compiles = self._compiles, 0
            csecs, self._compile_secs = self._compile_secs, 0.0
            cmax, self._compile_max = self._compile_max, 0.0
        out = {
            name: {
                "count": int(c),
                "total_ms": round(t * 1000.0, 3),
                "max_ms": round(m * 1000.0, 3),
            }
            for name, (c, t, m) in sorted(agg.items())
        }
        if compiles:
            out["compile"] = {
                "count": compiles,
                "total_ms": round(csecs * 1000.0, 3),
                "max_ms": round(cmax * 1000.0, 3),
            }
        return out

    def export(self, path: str,
               fragments: Sequence[str] = ()) -> Optional[str]:
        """Write the accumulated Chrome-trace events as a Perfetto-
        loadable ``trace.json`` (open at ui.perfetto.dev or
        chrome://tracing). Returns the path, or None when tracing is
        off. Events are NOT cleared — export is an end-of-run dump.

        ``fragments`` are sibling trace files written by OTHER
        processes of a multi-host run (the driver's ``trace.p<i>.json``
        per-host exports): their events are merged into this export so
        the timeline shows one lane group per host instead of silently
        reflecting process 0 only. Unreadable fragments are skipped —
        a host that crashed before exporting must not take down the
        survivors' merged trace."""
        if not self.trace:
            return None
        with self._lock:
            events = list(self._events)
        for frag in fragments:
            try:
                with open(frag) as f:
                    frag_events = json.load(f).get("traceEvents", [])
            except (OSError, ValueError):
                continue
            events.extend(
                e for e in frag_events if e.get("ph") != "M"
            )
        lanes = sorted({e.get("pid", 0) for e in events} | {self.process_index})
        doc = {
            "displayTimeUnit": "ms",
            "traceEvents": [
                *({"ph": "M", "pid": pid, "name": "process_name",
                   "args": {"name": f"colearn host {pid} round lifecycle"}}
                  for pid in lanes),
                *events,
            ],
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size > TRACE_SIZE_WARN_BYTES and not self._size_warned:
            # warn once: multi-GB traces from long runs are almost
            # never intentional and stall (or crash) the trace viewer
            self._size_warned = True
            logging.getLogger(__name__).warning(
                "exported trace %s is %.1f MiB (> %.0f MiB): long runs "
                "produce very large traces — lower run.obs."
                "trace_max_events or trace a shorter run",
                path, size / 2**20, TRACE_SIZE_WARN_BYTES / 2**20,
            )
        return path
