"""Benchmark harness (BASELINE.json:2): FL rounds/sec and
client-updates/sec/chip, plus MFU accounting (XLA-counted FLOPs vs the
chip's bf16 peak).

Default (what the driver runs): the headline config
``cifar10_fedavg_100`` — prints ONE JSON line::

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Matrix mode (VERDICT r2 missing-#4 — a perf record for every TPU
config, so regressions in those paths are measurable)::

    python bench.py --config femnist_fedprox_500   # one line, that config
    python bench.py --matrix                        # one line per config

``vs_baseline`` is relative to OUR first recorded TPU measurement of the
same config in BASELINE.md (the reference publishes no numbers —
BASELINE.json:13 ``"published": {}``); a config measured for the first
time reports vs_baseline=1.0 and its number becomes the baseline.
"""

from __future__ import annotations

import argparse
import json
import time

# First recorded rounds/sec per config on 1× TPU v5 lite (BASELINE.md
# measurements tables). The headline baseline is the 2026-07-29 S0-S2
# first light-up; the other configs' baselines are their round-3 first
# measurements.
BASELINES = {
    "cifar10_fedavg_100": 2.22,
    # round-3 first measurements through THIS bench path (BASELINE.md
    # round-3 table; the dispatch-bound configs vary ~2× with relay load)
    "cifar10_fedavg_1000": 3.05,
    "femnist_fedprox_500": 5.90,
    "shakespeare_fedavg": 6.71,
    "imagenet_silo_dp": 0.31,
}

# Dense bf16 peak of one TPU v5e (v5 lite) chip. MFU = achieved/peak; the
# FLOP count comes from XLA's cost model of ONE scan-free train step
# (fwd+bwd on one batch) × steps × cohort — see _round_flops for why the
# whole-round program can't be cost-analyzed directly.
PEAK_BF16_FLOPS = 197e12

# Per-config bench shape: (warmup rounds, timed rounds, extra overrides).
# Overrides only bound BENCH COST (round count, per-client caps, eval
# off) — engine, algorithm, model family, partition kind, and DP are the
# config's own. The imagenet cap keeps a ViT-B/16 DP round at seconds,
# not minutes; recorded in the JSON so the number is honest.
_SHAPES = {
    "cifar10_fedavg_100": (2, 16, {}),
    "cifar10_fedavg_1000": (2, 8, {}),
    "femnist_fedprox_500": (2, 8, {}),
    "shakespeare_fedavg": (2, 16, {}),
    "imagenet_silo_dp": (1, 3, {"data.max_examples_per_client": 128}),
}


def _round_flops(exp, state):
    """Analytic FLOPs of one round: XLA-counted FLOPs of a single
    SCAN-FREE train step (value_and_grad on one batch) × local steps ×
    cohort size. The whole-round program cannot be cost-analyzed
    directly — XLA's cost model counts a ``lax.scan`` body ONCE, not
    ×trip-count, under-reporting a 128-step round by ~128×. Optimizer
    + psum + server-update FLOPs are elementwise (≪1% of fwd+bwd) and
    ignored; DP's per-example gradients cost the same matmul FLOPs as
    the batched backward. Returns None if the backend has no cost model."""
    import jax
    import jax.numpy as jnp

    from colearn_federated_learning_tpu.client.trainer import make_loss_fn

    bs = exp.cfg.client.batch_size
    x = jnp.asarray(exp.fed.train_x[:bs])
    y = jnp.asarray(exp.fed.train_y[:bs])
    m = jnp.ones((bs,), jnp.float32)
    step = jax.value_and_grad(make_loss_fn(exp.model, exp.task))
    try:
        compiled = jax.jit(step).lower(state["params"], x, y, m).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        if not ca or "flops" not in ca:
            return None
        return float(ca["flops"]) * exp.shape.steps * exp.cfg.server.cohort_size
    except Exception:
        return None


def _hbm_stats():
    """Peak/in-use device memory if the backend exposes it (HBM headroom
    for the north-star scale record); None otherwise."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats() or {}
    except Exception:
        return None
    out = {}
    if "bytes_in_use" in stats:
        out["hbm_in_use_gib"] = round(stats["bytes_in_use"] / 2**30, 2)
    if "peak_bytes_in_use" in stats:
        out["hbm_peak_gib"] = round(stats["peak_bytes_in_use"] / 2**30, 2)
    if "bytes_limit" in stats:
        out["hbm_limit_gib"] = round(stats["bytes_limit"] / 2**30, 2)
    return out or None


def bench_config(name: str):
    import jax

    from colearn_federated_learning_tpu.config import get_named_config
    from colearn_federated_learning_tpu.server.round_driver import Experiment

    warmup, timed, overrides = _SHAPES[name]
    cfg = get_named_config(name)
    cfg.server.num_rounds = warmup + timed
    cfg.server.eval_every = 0
    cfg.server.checkpoint_every = 0
    cfg.run.out_dir = ""
    # synthetic corpora at the real datasets' cardinality (zero egress —
    # real files absent); the per-config synthetic sizes already match
    # except the 100-client config, pinned at CIFAR's 50k here
    if name == "cifar10_fedavg_100":
        cfg.data.synthetic_train_size = 50_000
        cfg.data.synthetic_test_size = 1_000
    cfg.apply_overrides(overrides)
    cfg.validate()

    exp = Experiment(cfg, echo=False)
    state = exp.init_state()
    state = exp._place_state(state)
    flops_per_round = _round_flops(exp, state)

    # Rounds are dispatched asynchronously (the driver's production mode:
    # run.metrics_flush_every batches metric fetches); the timed region
    # ends with ONE metrics drain, which forces execution of every round
    # (each depends on the previous round's params). block_until_ready
    # alone does not sync through the axon remote-execution relay.
    for r in range(warmup):
        state = exp.run_round(state, r)
        last_loss = float(state.pop("_metrics").train_loss)

    t0 = time.perf_counter()
    pending = []
    for r in range(warmup, warmup + timed):
        state = exp.run_round(state, r)
        pending.append(state.pop("_metrics"))
    fetched = jax.device_get(pending)
    last_loss = float(fetched[-1].train_loss)
    dt = time.perf_counter() - t0

    rounds_per_sec = timed / dt
    updates_per_sec_per_chip = (
        timed * cfg.server.cohort_size / dt / exp.n_chips
    )
    baseline = BASELINES.get(name)
    vs = rounds_per_sec / baseline if baseline else 1.0
    extra = {
        "client_updates_per_sec_per_chip": round(updates_per_sec_per_chip, 4),
        "n_chips": exp.n_chips,
        "timed_rounds": timed,
        "platform": jax.devices()[0].platform,
        "data_source": exp.fed.meta.get("source"),
        "final_train_loss": round(last_loss, 4),
        "param_dtype": cfg.run.param_dtype,
    }
    for k, v in overrides.items():
        extra[f"override:{k}"] = v
    if flops_per_round:
        achieved = flops_per_round * rounds_per_sec
        extra.update({
            "model_tflops_per_round": round(flops_per_round / 1e12, 3),
            "achieved_tflops": round(achieved / 1e12, 2),
            "mfu_pct": round(100.0 * achieved / (PEAK_BF16_FLOPS * exp.n_chips), 2),
        })
    hbm = _hbm_stats()
    if hbm:
        extra.update(hbm)
    d = cfg.data
    return {
        "metric": (
            f"FL rounds/sec ({d.num_clients}-client {d.name}, "
            f"{cfg.model.name}, cohort {cfg.server.cohort_size})"
        ),
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(vs, 4),
        "extra": extra,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="cifar10_fedavg_100",
                    choices=sorted(_SHAPES))
    ap.add_argument("--matrix", action="store_true",
                    help="bench every config; one JSON line each")
    args = ap.parse_args(argv)
    if not args.matrix:
        print(json.dumps(bench_config(args.config)), flush=True)
        return
    # Matrix mode re-execs one subprocess per config: each gets a clean
    # process (allocator stats aren't cumulative across configs, no
    # cross-config executable-cache contamination of HBM numbers).
    import subprocess
    import sys

    for name in sorted(_SHAPES):
        proc = subprocess.run(
            [sys.executable, __file__, "--config", name],
            capture_output=True, text=True,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        if proc.returncode != 0 or not line.startswith("{"):
            record = {"config": name, "error": proc.stderr[-500:]}
        else:
            record = dict(json.loads(line), config=name)
        print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
