"""Headline benchmark (BASELINE.json:2): FL rounds/sec and
client-updates/sec/chip on the 100-client CIFAR-10 ResNet-18 config,
plus MFU accounting (XLA-counted FLOPs vs the chip's bf16 peak).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` is relative to OUR first recorded TPU measurement in
BASELINE.md (the reference publishes no numbers — BASELINE.json:13
``"published": {}`` — so our own first light-up is the baseline the
driver tracks improvement against).
"""

from __future__ import annotations

import json
import time

# First recorded rounds/sec on 1× TPU v5 lite (see BASELINE.md measurements
# table): 2026-07-29, commit of milestone S0-S2. Later entries in that table
# track improvements against this number (bench reports vs_baseline).
BASELINE_ROUNDS_PER_SEC = 2.22

WARMUP_ROUNDS = 2
TIMED_ROUNDS = 8

# Dense bf16 peak of one TPU v5e (v5 lite) chip. MFU = achieved/peak; the
# FLOP count comes from XLA's cost model of ONE scan-free train step
# (fwd+bwd on one batch) × steps × cohort — see _round_flops for why the
# whole-round program can't be cost-analyzed directly.
PEAK_BF16_FLOPS = 197e12


def _round_flops(exp, state):
    """Analytic FLOPs of one round: XLA-counted FLOPs of a single
    SCAN-FREE train step (value_and_grad on one batch) × local steps ×
    cohort size. The whole-round program cannot be cost-analyzed
    directly — XLA's cost model counts a ``lax.scan`` body ONCE, not
    ×trip-count, under-reporting the 128-step round by ~128×. Optimizer
    + psum + server-update FLOPs are elementwise (≪1% of fwd+bwd) and
    ignored. Returns None if the backend exposes no cost model."""
    import jax
    import jax.numpy as jnp

    from colearn_federated_learning_tpu.client.trainer import make_loss_fn

    bs = exp.cfg.client.batch_size
    x = jnp.asarray(exp.fed.train_x[:bs])
    y = jnp.asarray(exp.fed.train_y[:bs])
    m = jnp.ones((bs,), jnp.float32)
    step = jax.value_and_grad(make_loss_fn(exp.model, exp.task))
    try:
        compiled = jax.jit(step).lower(state["params"], x, y, m).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        if not ca or "flops" not in ca:
            return None
        return float(ca["flops"]) * exp.shape.steps * exp.cfg.server.cohort_size
    except Exception:
        return None


def main():
    import jax

    from colearn_federated_learning_tpu.config import get_named_config
    from colearn_federated_learning_tpu.server.round_driver import Experiment

    cfg = get_named_config("cifar10_fedavg_100")
    cfg.server.num_rounds = WARMUP_ROUNDS + TIMED_ROUNDS
    cfg.server.eval_every = 0
    cfg.server.checkpoint_every = 0
    cfg.run.out_dir = ""
    # synthetic CIFAR-sized corpus (real CIFAR absent in this sandbox: zero
    # egress). Same shapes/cardinality as the real thing: 50k train examples.
    cfg.data.synthetic_train_size = 50_000
    cfg.data.synthetic_test_size = 1_000

    exp = Experiment(cfg, echo=False)
    state = exp.init_state()
    state = exp._place_state(state)
    flops_per_round = _round_flops(exp, state)

    # Rounds are dispatched asynchronously (the driver's production mode:
    # run.metrics_flush_every batches metric fetches); the timed region
    # ends with ONE metrics drain, which forces execution of every round
    # (each depends on the previous round's params). block_until_ready
    # alone does not sync through the axon remote-execution relay.
    for r in range(WARMUP_ROUNDS):
        state = exp.run_round(state, r)
        last_loss = float(state.pop("_metrics").train_loss)

    t0 = time.perf_counter()
    pending = []
    for r in range(WARMUP_ROUNDS, WARMUP_ROUNDS + TIMED_ROUNDS):
        state = exp.run_round(state, r)
        pending.append(state.pop("_metrics"))
    fetched = jax.device_get(pending)
    last_loss = float(fetched[-1].train_loss)
    dt = time.perf_counter() - t0

    rounds_per_sec = TIMED_ROUNDS / dt
    updates_per_sec_per_chip = (
        TIMED_ROUNDS * cfg.server.cohort_size / dt / exp.n_chips
    )
    vs = rounds_per_sec / BASELINE_ROUNDS_PER_SEC if BASELINE_ROUNDS_PER_SEC else 1.0
    extra = {
        "client_updates_per_sec_per_chip": round(updates_per_sec_per_chip, 4),
        "n_chips": exp.n_chips,
        "timed_rounds": TIMED_ROUNDS,
        "platform": jax.devices()[0].platform,
        "data_source": exp.fed.meta.get("source"),
        "final_train_loss": round(last_loss, 4),
        "param_dtype": cfg.run.param_dtype,
    }
    if flops_per_round:
        achieved = flops_per_round * rounds_per_sec
        extra.update({
            "model_tflops_per_round": round(flops_per_round / 1e12, 3),
            "achieved_tflops": round(achieved / 1e12, 2),
            "mfu_pct": round(100.0 * achieved / (PEAK_BF16_FLOPS * exp.n_chips), 2),
        })
    print(json.dumps({
        "metric": "FL rounds/sec (100-client CIFAR-10, ResNet-18, cohort 16)",
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(vs, 4),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
