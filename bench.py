"""Benchmark harness (BASELINE.json:2): FL rounds/sec and
client-updates/sec/chip, plus MFU accounting (XLA-counted FLOPs vs the
chip's bf16 peak).

Default (what the driver runs): the headline config
``cifar10_fedavg_100`` — prints ONE JSON line::

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Matrix mode (VERDICT r2 missing-#4 — a perf record for every TPU
config, so regressions in those paths are measurable)::

    python bench.py --config femnist_fedprox_500   # one line, that config
    python bench.py --matrix                        # one line per config

``vs_baseline`` is relative to OUR first recorded TPU measurement of the
same config in BASELINE.md (the reference publishes no numbers —
BASELINE.json:13 ``"published": {}``); a config measured for the first
time reports vs_baseline=1.0 and its number becomes the baseline.
"""

from __future__ import annotations

import argparse
import json
import os
import time

# First recorded rounds/sec per config on 1× TPU v5 lite (BASELINE.md
# measurements tables). The headline baseline is the 2026-07-29 S0-S2
# first light-up; the other configs' baselines are their round-3 first
# measurements.
BASELINES = {
    "cifar10_fedavg_100": 2.22,
    # round-3 first measurements through THIS bench path (BASELINE.md
    # round-3 table; the dispatch-bound configs vary ~2× with relay load)
    "cifar10_fedavg_1000": 3.05,
    # femnist/shakespeare RE-PINNED at the r5-adopted shapes (cohort 32;
    # shakespeare also fuse_rounds=10) — BASELINE.md r5 sweep table. The
    # old-shape values (5.90 / 6.71 at cohorts 16 / 8) are kept there;
    # client-updates/sec/chip improved 337→405 and 381→801.
    "femnist_fedprox_500": 12.66,
    "shakespeare_fedavg": 13.42,
    "imagenet_silo_dp": 0.31,
}

# Device-side ms/round baselines (from the round-4 profiled measurement,
# BASELINE.md r4 table). Wall r/s is mostly relay weather for
# dispatch-bound configs (MFU < 5%) — a 2× real regression could hide
# inside the relay's 2-3× load swing — so any config with a pinned
# device baseline gates vs_baseline on the round program's measured
# DEVICE time instead, which is weather-independent (VERDICT r3
# weak-#5). Under run.fuse_rounds the fused chunk's device time is
# divided by fuse, so the per-round pin survives shape re-pins.
DEVICE_MS_BASELINES = {
    # RE-PINNED r6 at the fused shapes (fuse adopted for the
    # dispatch-sensitive bench shapes this round): femnist cohort 32
    # (per-round device time is fusion-invariant — the scan body IS the
    # round program; r5 pin kept), shakespeare cohort 32 + fuse 10.
    "femnist_fedprox_500": 64.6,
    "shakespeare_fedavg": 29.5,
    # north-star config, pinned from the r4 profiled measurement
    # (~310 ms device/round, BASELINE.md "Workload" note): its wall r/s
    # swings with the relay even at 37% MFU, so the device gate is the
    # honest regression basis for it too
    "cifar10_fedavg_1000": 310.0,
}

# MFU floor below which a config counts as dispatch-bound (reported in
# the JSON; the device-time pass runs for every pinned config)
DISPATCH_BOUND_MFU_PCT = 5.0

# Chip peaks + the MFU-basis rule live in obs/roofline.py now (r8): the
# bench, the driver's `phase_cost_model` records, and `colearn mfu`'s
# waterfall all divide by the SAME denominators — a drifted copy here
# would make the waterfall's components stop summing to this headline.
# Re-exported under the established names (tests pin them).
from colearn_federated_learning_tpu.obs.roofline import (  # noqa: E402
    PEAK_BF16_FLOPS,
    PEAK_F32_FLOPS,
    host_exposed_pct as _host_exposed_pct,
    mfu_basis as _roofline_mfu_basis,
)


def _mfu_basis(cfg):
    """(basis name, peak FLOP/s) from the config's effective compute
    precision: the matmuls run bf16 when either the model compute dtype
    or the effective local-param dtype is bfloat16 (the shared
    obs/roofline.py rule — `mfu_basis` in every result's extra records
    which denominator produced the number)."""
    return _roofline_mfu_basis(
        cfg.run.compute_dtype, cfg.run.local_param_dtype,
        cfg.run.param_dtype,
    )

# Per-config bench shape: (warmup rounds, timed rounds, extra overrides).
# Overrides only bound BENCH COST (round count, per-client caps, eval
# off) — engine, algorithm, model family, partition kind, and DP are the
# config's own. The imagenet cap keeps a ViT-B/16 DP round at seconds,
# not minutes; recorded in the JSON so the number is honest.
_SHAPES = {
    # r7 (ROADMAP item 2 — the 41% MFU plateau): the headline config
    # adopts all three levers at once. fuse_rounds=4 amortizes the
    # ~13 ms host dispatch the r2 profile measured (the r2 R=8
    # fusion attempt predated the generalized fused engine; r6 proved
    # fuse=4 compiles fine for this exact model at cohort 64);
    # server.fused_apply collapses the round tail into one pallas
    # pass; run.double_buffer (default-on) hides host_inputs/placement
    # under dispatch. bf16-compute/f32-master was already the config's
    # dtype policy — now recorded via compute_dtype/mfu_basis extras.
    "cifar10_fedavg_100": (4, 16, {"run.fuse_rounds": 4,
                                   "server.fused_apply": True}),
    # ISSUE 18: the headline config's device-control-plane twin —
    # identical workload + fusion, but cohort/churn/slab derivation is
    # lowered into the round program (server/device_plane.py) so host
    # I/O collapses to flush boundaries. Bench-report's mode column and
    # the host_exposed_pct gate read the two entries side by side.
    "cifar10_fedavg_100_device": (4, 16, {"run.fuse_rounds": 4,
                                          "server.fused_apply": True,
                                          "run.control_plane": "device"}),
    # r6: round fusion adopted for the dispatch-sensitive shapes — the
    # generalized fused scan now covers robust/attack/EF paths, and the
    # plain configs take the dispatch amortization directly (warmup and
    # timed are fused-chunk multiples; fuse divides num_rounds)
    "cifar10_fedavg_1000": (4, 8, {"run.fuse_rounds": 4,
                                   "server.fused_apply": True}),
    # r7: femnist's natural-partition (power-law) client sizes make the
    # federation-max pad mostly dead steps for the median cohort —
    # shape buckets trim them per chunk (bitwise-equal; the grid is
    # recorded in extra.shape_bucket_steps so the number stays honest)
    "femnist_fedprox_500": (4, 8, {"run.fuse_rounds": 4,
                                   "run.shape_buckets.enabled": True}),
    # shakespeare runs fused via its named config (run.fuse_rounds=10)
    "shakespeare_fedavg": (10, 20, {}),
    "imagenet_silo_dp": (1, 3, {"data.max_examples_per_client": 128}),
}


def _base_shape_name(name: str) -> str:
    # the *_device twins bench a named config under the device control
    # plane — same workload, the mode override rides in the entry's
    # overrides dict
    return name[: -len("_device")] if name.endswith("_device") else name


def _round_flops(exp, state):
    """Analytic FLOPs of one round: XLA-counted FLOPs of a single
    SCAN-FREE train step (value_and_grad on one batch) × local steps ×
    cohort size. The whole-round program cannot be cost-analyzed
    directly — XLA's cost model counts a ``lax.scan`` body ONCE, not
    ×trip-count, under-reporting a 128-step round by ~128×. Optimizer
    + psum + server-update FLOPs are elementwise (≪1% of fwd+bwd) and
    ignored; DP's per-example gradients cost the same matmul FLOPs as
    the batched backward. Returns None if the backend has no cost model."""
    import jax
    import jax.numpy as jnp

    from colearn_federated_learning_tpu.client.trainer import make_loss_fn

    bs = exp.cfg.client.batch_size
    x = jnp.asarray(exp.fed.train_x[:bs])
    y = jnp.asarray(exp.fed.train_y[:bs])
    m = jnp.ones((bs,), jnp.float32)
    step = jax.value_and_grad(make_loss_fn(exp.model, exp.task))
    try:
        compiled = jax.jit(step).lower(state["params"], x, y, m).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        if not ca or "flops" not in ca:
            return None
        return float(ca["flops"]) * exp.shape.steps * exp.cfg.server.cohort_size
    except Exception:
        return None


def _parse_device_ms(profile_dir: str, fn_prefix: str = "jit_round_fn"):
    """Mean duration (ms) of the round program's DEVICE executions in a
    ``jax.profiler`` trace directory.

    The perfetto trace contains ``jit_round_fn`` spans on both the host
    (dispatch, ~ms) and the device (execution, the number we want); the
    device track is identified as the pid whose spans carry the most
    total time — dispatch spans are orders of magnitude shorter than
    executions for every config benched here. Returns None when no
    trace or no matching spans exist."""
    import glob
    import gzip
    import json as _json

    events = []
    for pattern in ("*.trace.json.gz", "*.trace.json"):
        for path in glob.glob(
            os.path.join(profile_dir, "**", pattern), recursive=True
        ):
            opener = gzip.open if path.endswith(".gz") else open
            try:
                with opener(path, "rt") as f:
                    events.extend(_json.load(f).get("traceEvents", []))
            except Exception:
                continue
    by_pid = {}
    for e in events:
        if e.get("ph") == "X" and str(e.get("name", "")).startswith(fn_prefix):
            by_pid.setdefault(e.get("pid"), []).append(float(e.get("dur", 0)))
    if not by_pid:
        return None
    durs = max(by_pid.values(), key=sum)
    return sum(durs) / len(durs) / 1000.0  # µs → ms


def _measure_device_ms(exp, state, start_round: int, rounds: int = 4):
    """Trace ``rounds`` dispatched rounds and return (state, mean device
    ms/round). The drain inside the trace forces execution so the trace
    contains the device work (block_until_ready does not force through
    the axon relay)."""
    import shutil
    import tempfile

    import jax

    tmp = tempfile.mkdtemp(prefix="bench_profile_")
    fuse = exp.cfg.run.fuse_rounds
    try:
        jax.profiler.start_trace(tmp)
        pending = []
        for r in range(start_round, start_round + rounds * fuse, fuse):
            state = exp.run_round(state, r)
            pending.append(state.pop("_metrics"))
        jax.device_get(pending)
        jax.profiler.stop_trace()
        ms = _parse_device_ms(tmp)
        # ``rounds`` DISPATCHES; under fusion each carries fuse rounds
        return state, (ms / fuse if ms is not None else None)
    except Exception:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        return state, None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _gate(name: str, rounds_per_sec: float, device_ms, mfu_pct):
    """(vs_baseline, basis): baseline_ms / measured_ms whenever a
    device-time baseline is pinned and the device pass produced a
    measurement — device time regresses independently of relay weather,
    so it is the honest basis for every pinned config (dispatch-bound
    or not; ``mfu_pct`` is reported but no longer gates the basis).
    Wall-clock r/s against BASELINES otherwise. Pure function so the
    2×-regression-trips-the-gate property is unit-testable."""
    if device_ms and name in DEVICE_MS_BASELINES:
        return DEVICE_MS_BASELINES[name] / device_ms, "device_ms"
    baseline = BASELINES.get(name)
    return (rounds_per_sec / baseline if baseline else 1.0), "rounds_per_sec"


_STATIC_CHECK_CACHE = None


def _static_check_extra():
    """Static-analyzer provenance for every bench entry's extra
    (ISSUE 13): the analyzer version + whether `colearn check` passed
    clean on the repo producing this number. Computed once per process
    (the capability extraction runs ~600 validate() calls); best-effort
    — a broken analyzer must never take the bench down."""
    global _STATIC_CHECK_CACHE
    if _STATIC_CHECK_CACHE is None:
        from colearn_federated_learning_tpu.analysis.check import (
            bench_provenance,
        )

        _STATIC_CHECK_CACHE = bench_provenance()
    return _STATIC_CHECK_CACHE


def _peak_host_rss_mb():
    """Peak resident set size of THIS process (ru_maxrss; KiB on
    Linux). Recorded in every result's extra so the BENCH trajectory
    carries the clients-scale axis next to rounds/sec — the ROADMAP
    item-1 acceptance (`store_scale_1m` flat vs `store_scale_1k`) is
    read directly off these numbers. Matrix mode runs one subprocess
    per config, so each peak is that config's own."""
    import resource
    import sys

    kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    if sys.platform == "darwin":  # ru_maxrss is bytes on macOS
        kb /= 1024.0
    return round(kb / 1024.0, 1)


def _hbm_stats():
    """Peak/in-use device memory if the backend exposes it (HBM headroom
    for the north-star scale record); None otherwise."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats() or {}
    except Exception:
        return None
    out = {}
    if "bytes_in_use" in stats:
        out["hbm_in_use_gib"] = round(stats["bytes_in_use"] / 2**30, 2)
    if "peak_bytes_in_use" in stats:
        out["hbm_peak_gib"] = round(stats["peak_bytes_in_use"] / 2**30, 2)
    if "bytes_limit" in stats:
        out["hbm_limit_gib"] = round(stats["bytes_limit"] / 2**30, 2)
    return out or None


def bench_config(name: str):
    import jax

    from colearn_federated_learning_tpu.config import get_named_config
    from colearn_federated_learning_tpu.server.round_driver import Experiment

    warmup, timed, overrides = _SHAPES[name]
    base_name = _base_shape_name(name)
    cfg = get_named_config(base_name)
    cfg.server.num_rounds = warmup + timed
    cfg.server.eval_every = 0
    cfg.server.checkpoint_every = 0
    cfg.run.out_dir = ""
    # synthetic corpora at the real datasets' cardinality (zero egress —
    # real files absent); the per-config synthetic sizes already match
    # except the 100-client config, pinned at CIFAR's 50k here
    if base_name == "cifar10_fedavg_100":
        cfg.data.synthetic_train_size = 50_000
        cfg.data.synthetic_test_size = 1_000
    cfg.apply_overrides(overrides)
    cfg.validate()

    exp = Experiment(cfg, echo=False)
    state = exp.init_state()
    state = exp._place_state(state)
    flops_per_round = _round_flops(exp, state)

    # Rounds are dispatched asynchronously (the driver's production mode:
    # run.metrics_flush_every batches metric fetches); the timed region
    # ends with ONE metrics drain, which forces execution of every round
    # (each depends on the previous round's params). block_until_ready
    # alone does not sync through the axon remote-execution relay.
    fuse = cfg.run.fuse_rounds
    # each dispatch executes exactly `fuse` rounds — misaligned shape
    # constants would silently mis-count rounds_per_sec
    assert warmup % fuse == 0 and timed % fuse == 0, (name, warmup, timed, fuse)
    # the executable registry intercepts lowerings only while installed
    # (fit() does this for real runs); bench drives run_round directly,
    # so install around the round loops to get the HLO-derived flop
    # truth behind the flop_model_drift_pct extra — production runs
    # have it on too, so the timed region stays representative
    from colearn_federated_learning_tpu.obs import executables as _exec_mod

    if exp._exec_reg is not None:
        _exec_mod.install(exp._exec_reg)
    try:
        for r in range(0, warmup, fuse):
            state = exp.run_round(state, r)
            m = state.pop("_metrics")
            last_loss = float(
                m.train_loss if fuse == 1 else m.train_loss[-1]
            )

        # reset the phase-span aggregates so the breakdown below covers
        # the TIMED region only (the warmup window holds the compiles)
        exp.tracer.drain()
        t0 = time.perf_counter()
        pending = []
        for r in range(warmup, warmup + timed, fuse):
            state = exp.run_round(state, r)
            m = state.pop("_metrics")
            if fuse == 1:
                pending.append(m)
            else:
                pending.extend(
                    jax.tree.map(lambda a, j=j: a[j], m) for j in range(fuse)
                )
        fetched = jax.device_get(pending)
        last_loss = float(fetched[-1].train_loss)
        dt = time.perf_counter() - t0
    finally:
        if exp._exec_reg is not None:
            _exec_mod.uninstall()

    rounds_per_sec = timed / dt
    updates_per_sec_per_chip = (
        timed * cfg.server.cohort_size / dt / exp.n_chips
    )
    mfu_basis, peak_flops = _mfu_basis(cfg)
    flops_pct = None
    if flops_per_round:
        flops_pct = (
            100.0 * flops_per_round * rounds_per_sec
            / (peak_flops * exp.n_chips)
        )
    # per-phase host-side timing of the timed region (obs/spans.py):
    # localizes a wall-clock regression to host inputs / placement /
    # dispatch (or a mid-bench retrace) without a profiler rerun —
    # drained BEFORE the device-time pass dispatches extra rounds
    timed_compiles = exp.tracer.compile_stats()[0]
    phase_ms = {
        k: v["total_ms"] for k, v in exp.tracer.drain().items()
    }
    # device-time pass for gating: every config with a pinned device
    # baseline gets the weather-independent basis (4 profiled dispatches
    # — cheap next to the timed region)
    device_ms = None
    if name in DEVICE_MS_BASELINES:
        state, device_ms = _measure_device_ms(exp, state, warmup + timed)
    vs, vs_basis = _gate(name, rounds_per_sec, device_ms, flops_pct)
    # host-exposed share of the timed wall (obs/roofline.py rule):
    # the observability-tax number bench-report gates against
    # host_exposed_pct_max — host spans the device idles through,
    # over the timed region's wall clock
    hep = _host_exposed_pct(phase_ms, dt)
    # measured-vs-analytic flop drift (run.obs.executables): the XLA
    # cost_analysis flops of the dominant compiled round program vs the
    # analytic model — None (n/a in bench-report) when the registry is
    # off or the backend reports no cost analysis, gated against
    # flop_drift_pct_max
    drift_pct = None
    reg = getattr(exp, "_exec_reg", None)
    if reg is not None and flops_per_round:
        measured = reg.measured_round_flops()
        if measured is not None:
            drift_pct = round(
                100.0 * (measured[1] - flops_per_round) / flops_per_round, 2
            )
    extra = {
        "static_check": _static_check_extra(),
        "vs_baseline_basis": vs_basis,
        "phase_ms": phase_ms,
        "host_exposed_pct": None if hep is None else round(hep, 2),
        "flop_model_drift_pct": drift_pct,
        "client_updates_per_sec_per_chip": round(updates_per_sec_per_chip, 4),
        "n_chips": exp.n_chips,
        "timed_rounds": timed,
        "platform": jax.devices()[0].platform,
        "data_source": exp.fed.meta.get("source"),
        # clients-scale axis (ROADMAP item 1): every result records the
        # federation size and this process's peak host RSS, so the
        # BENCH trajectory shows host memory tracking O(cohort), not
        # O(num_clients), as the store-backed entries scale up
        "num_clients": cfg.data.num_clients,
        "peak_host_rss_mb": _peak_host_rss_mb(),
        "final_train_loss": round(last_loss, 4),
        "param_dtype": cfg.run.param_dtype,
        # precision provenance (r7, ROADMAP item 2): which dtype the
        # matmuls ran in and which peak the MFU divides by — a bf16
        # number silently compared against an f32 denominator (or vice
        # versa) is the exact hygiene failure mfu_basis exists to stop
        "compute_dtype": cfg.run.compute_dtype,
        "mfu_basis": mfu_basis,
        "peak_tflops": round(peak_flops / 1e12, 1),
        "fused_apply": bool(cfg.server.fused_apply),
        "double_buffer": bool(cfg.run.double_buffer),
        # shape provenance (r6): fuse_rounds and the local-training
        # dtype change the meaning of every throughput number — record
        # them in each result so the BENCH_*.json trajectory stays
        # interpretable across shape re-pins
        "fuse_rounds": cfg.run.fuse_rounds,
        "local_param_dtype": cfg.run.local_param_dtype,
        # cohort layout (r12): megabatch collapses the cohort axis into
        # the GEMM batch — throughput/MFU numbers under the two layouts
        # are different machines, so every result records which one ran
        "cohort_layout": cfg.run.cohort_layout,
        # control plane (ISSUE 18): device mode derives cohorts/churn in
        # the round program, so the host-exposed share is a different
        # machine — every result records which plane produced it
        "control_plane": cfg.run.control_plane,
        # the per-client forensic ledger adds an in-program stats block
        # + scatter to every round — throughput numbers with it on are
        # not comparable to ledger-off pins, so record the switch
        "client_ledger": bool(cfg.run.obs.client_ledger.enabled),
        # cohort-selection mode and reputation weighting (r8): adaptive
        # sampling changes which clients (and so which shard shapes) the
        # timed rounds draw, and reputation adds the in-program trust
        # computation — both shift throughput semantics, so every result
        # records them next to the ledger switch
        "sampler": cfg.server.sampling,
        "reputation": bool(cfg.server.reputation.enabled),
        # federation health observatory (run.obs.population): per-window
        # population_health records add small host-side accounting to
        # every round — record the switch so throughput numbers stay
        # comparable across BENCH entries
        "population": bool(cfg.run.obs.population.enabled),
        # LoRA adapter plane (model.lora): adapter-only uploads change
        # both the wire story and the per-round compute — every result
        # records the switch and the analytic full÷adapter upload-byte
        # ratio (exactly 1.0 with lora off)
        "lora": bool(cfg.model.lora.enabled),
        "wire_reduction_vs_full": round(exp.wire_reduction_vs_full(), 2),
        # trace-shaped churn (run.churn): availability gating + failure
        # injection change which clients (and how much work) the timed
        # rounds see — every result records the switch
        "churn": bool(cfg.run.churn.enabled),
    }
    for k, v in overrides.items():
        extra[f"override:{k}"] = v
    if device_ms is not None:
        extra["device_ms_per_round"] = round(device_ms, 3)
    extra["dispatch_bound"] = bool(
        flops_pct is None or flops_pct < DISPATCH_BOUND_MFU_PCT
    )
    # Shape-waste accounting (r7): which step grids the timed rounds
    # actually dispatched on, and how much of the padded grid was dead
    # work — so a BENCH_* trajectory can attribute a throughput move to
    # shape waste (or a bucket re-pin) rather than the kernels.
    import numpy as _np

    shape_stats = [
        exp._comm_stats.get(r) for r in range(warmup, warmup + timed)
    ]
    shape_stats = [s for s in shape_stats if s]
    if shape_stats and "padded_step_fraction" in shape_stats[0]:
        extra["padded_step_fraction"] = round(float(_np.mean(
            [s["padded_step_fraction"] for s in shape_stats]
        )), 4)
        extra["host_input_bytes_per_round"] = int(_np.mean(
            [s["host_input_bytes"] for s in shape_stats]
        ))
    extra["shape_bucket_steps"] = sorted({
        int(s["shape_bucket_steps"]) for s in shape_stats
        if "shape_bucket_steps" in s
    }) or [exp.shape.steps]
    if exp._bucket_ladder is not None:
        # compile budget: ≤ ladder-size retraces per engine; a NONZERO
        # timed-region compile count means a rung first realized inside
        # the timed window — visible here and as phase_ms["compile"]
        extra["shape_bucket_ladder_steps"] = [
            r * cfg.client.local_epochs for r in exp._bucket_ladder
        ]
        extra["timed_region_compiles"] = int(timed_compiles)
        assert len(exp._seen_buckets) <= len(exp._bucket_ladder), (
            exp._seen_buckets, exp._bucket_ladder
        )
    if flops_per_round:
        # raw MFU counts the FULL padded federation-max grid as useful
        # work (the legacy accounting); effective MFU mask-weights it —
        # only real examples' step FLOPs count, so the gap between the
        # two IS the padded-FLOP waste shape buckets reclaim
        step_flops = flops_per_round / (exp.shape.steps * cfg.server.cohort_size)
        mean_examples = float(_np.mean([float(m.examples) for m in fetched]))
        useful_flops = step_flops * mean_examples / cfg.client.batch_size
        extra.update({
            "model_tflops_per_round": round(flops_per_round / 1e12, 3),
            "achieved_tflops": round(flops_per_round * rounds_per_sec / 1e12, 2),
            "mfu_pct": round(flops_pct, 2),
            "effective_mfu_pct": round(
                100.0 * useful_flops * rounds_per_sec
                / (peak_flops * exp.n_chips), 2
            ),
        })
    if name == "cifar10_fedavg_100":
        # ROADMAP item 2's stated goal for the headline config — the
        # measured step above it (or short of it) is the honest record
        extra["roadmap_target"] = {"mfu_pct": 50.0, "vs_baseline": 2.0}
    hbm = _hbm_stats()
    if hbm:
        extra.update(hbm)
    d = cfg.data
    return {
        "metric": (
            f"FL rounds/sec ({d.num_clients}-client {d.name}, "
            f"{cfg.model.name}, cohort {cfg.server.cohort_size})"
        ),
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(vs, 4),
        "extra": extra,
    }


# Clients-scale entries (ROADMAP item 1 acceptance): the same tiny
# store-backed workload at 10³ and 10⁶ clients — streaming sampler,
# stream placement, mmap store — so the BENCH trajectory records host
# RSS staying flat (within 1.5×) while num_clients grows 1000×. Built
# on the fly into a temp dir (a 10⁶-client store of 2×(12,12,1)-uint8
# records is ~290 MB of DISK, a few MB of touched pages).
_STORE_SCALE = {
    "store_scale_1k": 1_000,
    "store_scale_1m": 1_000_000,
}

# Weak-scaling entries (ROADMAP item 1 follow-on / ISSUE 12): the SAME
# per-chip workload — the headline ResNet-18 family under the megabatch
# cohort layout, K_local clients per chip — run at however many chips
# are visible, so the BENCH trajectory finally gets an `n_chips` axis.
# The realized cohort is per_chip × n_chips (cohort-in-the-hundreds on
# a multi-chip slice; on 1 chip the entry IS the 1-chip pin the
# `colearn bench-report` weak-scaling-efficiency line divides by).
# Ideal weak scaling holds updates/sec/chip flat as chips grow.
_WEAK_SCALE = {
    "weak_scale_64": 64,
    "weak_scale_128": 128,
    "weak_scale_256": 256,
}


def _weak_scale_cfg(per_chip: int, n_chips: int, warmup: int, timed: int):
    """The weak-scale workload for one (per-chip cohort, chip count)
    point — factored out so CI can validate every entry's config
    without paying for a ResNet run."""
    from colearn_federated_learning_tpu.config import get_named_config

    cohort = per_chip * n_chips
    cfg = get_named_config("cifar10_fedavg_100")
    cfg.apply_overrides({
        # federation sized 2× the cohort so sampling stays a real draw;
        # the 50k corpus keeps shards non-degenerate up to 2048 clients
        "data.num_clients": 2 * cohort,
        "data.synthetic_train_size": 50_000,
        "data.synthetic_test_size": 1_000,
        # bounded per-chip step grid: 2 steps × batch 32 per client —
        # the megabatch block still sees K_local·32 GEMM rows per chip
        "data.max_examples_per_client": 64,
        "client.batch_size": 32,
        "server.cohort_size": cohort,
        "server.num_rounds": warmup + timed,
        "server.eval_every": 0,
        "server.checkpoint_every": 0,
        "run.out_dir": "",
        "run.fuse_rounds": 1,
        "run.cohort_layout": "megabatch",
        "server.fused_apply": True,
    })
    return cfg.validate()


def bench_weak_scale(name: str):
    import jax

    from colearn_federated_learning_tpu.server.round_driver import Experiment

    per_chip = _WEAK_SCALE[name]
    n_chips = len(jax.devices())
    cohort = per_chip * n_chips
    warmup, timed = 2, 4
    cfg = _weak_scale_cfg(per_chip, n_chips, warmup, timed)
    exp = Experiment(cfg, echo=False)
    state = exp._place_state(exp.init_state())
    flops_per_round = _round_flops(exp, state)
    for r in range(warmup):
        state = exp.run_round(state, r)
        state.pop("_metrics")
    t0 = time.perf_counter()
    pending = []
    for r in range(warmup, warmup + timed):
        state = exp.run_round(state, r)
        pending.append(state.pop("_metrics"))
    fetched = jax.device_get(pending)
    dt = time.perf_counter() - t0
    rounds_per_sec = timed / dt
    ups_chip = timed * cohort / dt / exp.n_chips
    basis, peak_flops = _mfu_basis(cfg)
    extra = {
        "static_check": _static_check_extra(),
        "weak_scale_per_chip_cohort": per_chip,
        "cohort_size": cohort,
        "n_chips": exp.n_chips,
        "client_updates_per_sec_per_chip": round(ups_chip, 4),
        "cohort_layout": cfg.run.cohort_layout,
        "control_plane": cfg.run.control_plane,
        "fused_apply": bool(cfg.server.fused_apply),
        "num_clients": cfg.data.num_clients,
        "timed_rounds": timed,
        "platform": jax.devices()[0].platform,
        "compute_dtype": cfg.run.compute_dtype,
        "local_param_dtype": cfg.run.local_param_dtype,
        "mfu_basis": basis,
        "peak_host_rss_mb": _peak_host_rss_mb(),
        "final_train_loss": round(float(fetched[-1].train_loss), 4),
        "lora": False,
        "wire_reduction_vs_full": round(exp.wire_reduction_vs_full(), 2),
        "churn": bool(cfg.run.churn.enabled),
    }
    if flops_per_round:
        extra["model_tflops_per_round"] = round(flops_per_round / 1e12, 3)
        extra["mfu_pct"] = round(
            100.0 * flops_per_round * rounds_per_sec
            / (peak_flops * exp.n_chips), 2
        )
    hbm = _hbm_stats()
    if hbm:
        extra.update(hbm)
    return {
        "metric": (
            f"FL rounds/sec (weak scaling: {per_chip} clients/chip x "
            f"{exp.n_chips} chip(s), resnet18, megabatch cohort {cohort})"
        ),
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec",
        # a weak-scale entry's regression basis is the efficiency line
        # in `colearn bench-report`, not a scalar baseline ratio
        "vs_baseline": 1.0,
        "extra": extra,
    }


# Async-throughput entry (ROADMAP item 4 acceptance): the promoted
# FedBuff plane under production traffic — 10³-client mmap store,
# stream placement, streaming-sampler arrivals, per-insert ledger +
# reputation merge, diurnal churn + dropout hazard + crash injection.
# The headline number is updates/sec ABSORBED at the configured
# staleness bound (clamped admissions counted, never silently
# included as bounded), recorded next to rounds/sec. BENCH_BUDGETS.json
# carries its floor (`async_updates_per_sec_min`); the entry records
# whether it was met so the trajectory gates on it.
_ASYNC_SCALE = {
    "async_throughput_1k": 1_000,
}


def bench_async_throughput(name: str):
    import shutil
    import tempfile

    import jax

    from colearn_federated_learning_tpu.config import get_named_config
    from colearn_federated_learning_tpu.data.store import (
        build_synthetic_store,
    )
    from colearn_federated_learning_tpu.server.round_driver import Experiment

    n = _ASYNC_SCALE[name]
    warmup, timed = 2, 8
    s_max = 2
    tmp = tempfile.mkdtemp(prefix=f"bench_{name}_")
    try:
        t_build0 = time.perf_counter()
        build_synthetic_store(
            tmp, num_clients=n, examples_per_client=2, shape=(12, 12, 1),
            num_classes=10, seed=0, test_examples=64,
        )
        build_sec = time.perf_counter() - t_build0
        cfg = get_named_config("mnist_fedavg_2")
        cfg.apply_overrides({
            "algorithm": "fedbuff",
            "data.num_clients": n, "data.store.dir": tmp,
            "data.placement": "stream", "server.sampling": "streaming",
            "server.cohort_size": 16, "client.batch_size": 2,
            "server.num_rounds": warmup + timed, "server.eval_every": 0,
            "server.checkpoint_every": 0, "run.out_dir": "",
            "server.async_max_staleness": s_max,
            "server.async_backlog_cap": 8,
            # per-insert ledger stats feed the reputation-weighted merge
            # and the streaming sampler's arrival sketch
            "run.obs.client_ledger.enabled": True,
            "run.obs.client_ledger.log_every": 2,
            "server.reputation.enabled": True,
            "run.obs.population.enabled": True,
            # trace-shaped production traffic: diurnal wave + dropout
            # hazard + crash injection (seed-pure, resume-replayable)
            "run.churn.enabled": True,
            "run.churn.diurnal_period": 8,
            "run.churn.base_availability": 0.7,
            "run.churn.dropout_hazard": 0.02,
            "run.churn.crash_rate": 0.05,
        })
        cfg.validate()
        exp = Experiment(cfg, echo=False)
        state = exp._place_state(exp.init_state())
        for r in range(warmup):
            state = exp.run_round(state, r)
            exp._ledger_ref = state.get("ledger")
            state.pop("_metrics")
        absorbed0 = exp._async_absorbed
        t0 = time.perf_counter()
        pending = []
        for r in range(warmup, warmup + timed):
            state = exp.run_round(state, r)
            exp._ledger_ref = state.get("ledger")
            pending.append(state.pop("_metrics"))
        fetched = jax.device_get(pending)
        dt = time.perf_counter() - t0
        absorbed = exp._async_absorbed - absorbed0
        astats = [exp._async_stats[r] for r in range(warmup, warmup + timed)
                  if r in exp._async_stats]
        max_stale = max((a["max"] for a in astats), default=0)
        clamped = sum(a["clamped"] for a in astats)
        bp = sum(a["bp_dropped"] + a["bp_rejected"] for a in astats)
        updates_per_sec = absorbed / dt if dt > 0 else 0.0
        # the BENCH_BUDGETS floor for this entry (satellite: the async
        # throughput number is trajectory-gated like rounds/sec)
        floor = None
        try:
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "BENCH_BUDGETS.json")) as f:
                floor = json.load(f).get("async_updates_per_sec_min")
        except (OSError, json.JSONDecodeError):
            pass
        pop_totals = exp._population.summary_totals(
            None, (exp.fed.train_x, exp.fed.train_y)
        )
        return {
            "metric": (
                f"async updates/sec absorbed at staleness <= {2 * s_max} "
                f"({n}-client mmap store, fedbuff + churn, buffer "
                f"{cfg.server.cohort_size}, streaming sampler)"
            ),
            "value": round(updates_per_sec, 4),
            "unit": "updates/sec",
            "vs_baseline": 1.0,
            "extra": {
                "static_check": _static_check_extra(),
                "num_clients": n,
                "store_backed": True,
                "store_build_sec": round(build_sec, 2),
                "placement": "stream",
                "sampler": "streaming",
                "client_ledger": True,
                "reputation": True,
                "population": True,
                "churn": True,
                "platform": jax.devices()[0].platform,
                "timed_rounds": timed,
                "rounds_per_sec": round(timed / dt, 4) if dt > 0 else 0.0,
                "updates_absorbed": int(absorbed),
                "staleness_bound": 2 * s_max,
                "max_realized_staleness": int(max_stale),
                # pooled per-update staleness quantiles over the timed
                # window (exact — the driver keeps a value → count
                # histogram, no sampling)
                "staleness_p50": exp._staleness_percentiles()[0],
                "staleness_p90": exp._staleness_percentiles()[1],
                "staleness_clamped": int(clamped),
                "backpressure_shed": int(bp),
                "async_overload_policy": cfg.server.async_overload_policy,
                "final_train_loss": round(
                    float(fetched[-1].train_loss), 4
                ),
                "peak_host_rss_mb": _peak_host_rss_mb(),
                "coverage_pct": pop_totals.get("population_coverage_pct"),
                "gather_workers": pop_totals.get("store_gather_workers"),
                "store_gather_mbps": pop_totals.get("store_gather_mbps"),
                "budget_floor_updates_per_sec": floor,
                "meets_budget": (
                    bool(updates_per_sec >= float(floor))
                    if floor is not None else None
                ),
                "lora": False,
                "cohort_layout": cfg.run.cohort_layout,
                "control_plane": cfg.run.control_plane,
                "wire_reduction_vs_full": round(
                    exp.wire_reduction_vs_full(), 2
                ),
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# Hierarchical multi-version async entry (ISSUE 16 acceptance): the
# FedBuff plane at 10⁶ store-backed clients with TWO concurrent model
# versions (server.async_versions), FOUR edge aggregators grouping the
# popped buffer (server.hierarchy, reputation-trust core, 10% edge
# dropout), and trace-replay availability (run.churn.trace) instead of
# the analytic diurnal model. Headline: updates/sec ABSORBED at the
# staleness bound; extras break the absorbed count down per tier (edge)
# and per version. BENCH_BUDGETS.json gates it TWICE — the throughput
# floor (`async_updates_per_sec_min`) and the realized-staleness
# ceiling (`hier_async_staleness_bound`) — so a regression that keeps
# throughput by letting staleness run away still fails the report.
_HIER_ASYNC_SCALE = {
    "hier_async_1m": 1_000_000,
}


def bench_hier_async(name: str):
    import shutil
    import tempfile

    import jax

    from colearn_federated_learning_tpu.config import get_named_config
    from colearn_federated_learning_tpu.data.store import (
        build_synthetic_store,
    )
    from colearn_federated_learning_tpu.server.churn import (
        build_synthetic_trace,
    )
    from colearn_federated_learning_tpu.server.round_driver import Experiment

    n = _HIER_ASYNC_SCALE[name]
    warmup, timed = 2, 8
    s_max, versions, edges = 2, 2, 4
    tmp = tempfile.mkdtemp(prefix=f"bench_{name}_")
    try:
        t_build0 = time.perf_counter()
        build_synthetic_store(
            tmp, num_clients=n, examples_per_client=2, shape=(12, 12, 1),
            num_classes=10, seed=0, test_examples=64,
        )
        build_sec = time.perf_counter() - t_build0
        trace = build_synthetic_trace(
            os.path.join(tmp, "avail_trace"), rounds=64, rows=4096,
            seed=0, diurnal_period=8,
        )
        cfg = get_named_config("mnist_fedavg_2")
        cfg.apply_overrides({
            "algorithm": "fedbuff",
            "data.num_clients": n, "data.store.dir": tmp,
            "data.placement": "stream", "server.sampling": "streaming",
            "server.cohort_size": 16, "client.batch_size": 2,
            "server.num_rounds": warmup + timed, "server.eval_every": 0,
            "server.checkpoint_every": 0, "run.out_dir": "",
            "server.async_max_staleness": s_max,
            "server.async_backlog_cap": 8,
            # the tentpole knobs: concurrent model lines + edge tier
            "server.async_versions": versions,
            "server.async_retire_rounds": 6,
            "server.hierarchy.num_edges": edges,
            "server.hierarchy.core_aggregator": "reputation",
            "server.hierarchy.edge_dropout_rate": 0.1,
            "run.obs.population.enabled": True,
            # availability from a recorded on/off trace, not the
            # analytic diurnal wave (seed-pure row hash, O(cohort))
            "run.churn.enabled": True,
            "run.churn.trace": trace,
            "run.churn.dropout_hazard": 0.02,
        })
        cfg.validate()
        exp = Experiment(cfg, echo=False)
        state = exp._place_state(exp.init_state())
        for r in range(warmup):
            state = exp.run_round(state, r)
            state.pop("_metrics")
        absorbed0 = exp._async_absorbed
        t0 = time.perf_counter()
        pending = []
        for r in range(warmup, warmup + timed):
            state = exp.run_round(state, r)
            pending.append(state.pop("_metrics"))
        fetched = jax.device_get(pending)
        dt = time.perf_counter() - t0
        absorbed = exp._async_absorbed - absorbed0
        astats = [exp._async_stats[r] for r in range(warmup, warmup + timed)
                  if r in exp._async_stats]
        max_stale = max((a["max"] for a in astats), default=0)
        p50, p90, _hist_max = exp._staleness_percentiles()
        updates_per_sec = absorbed / dt if dt > 0 else 0.0
        floor = bound = None
        try:
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "BENCH_BUDGETS.json")) as f:
                budgets = json.load(f)
            floor = budgets.get("async_updates_per_sec_min")
            bound = budgets.get("hier_async_staleness_bound")
        except (OSError, json.JSONDecodeError):
            pass
        meets = None
        if floor is not None or bound is not None:
            meets = bool(
                (floor is None or updates_per_sec >= float(floor))
                and (bound is None or max_stale <= int(bound))
            )
        pop_totals = exp._population.summary_totals(
            None, (exp.fed.train_x, exp.fed.train_y)
        )
        return {
            "metric": (
                f"hier async updates/sec absorbed at staleness <= "
                f"{2 * s_max} ({n}-client mmap store, fedbuff × "
                f"{versions} versions × {edges} edges, trace churn)"
            ),
            "value": round(updates_per_sec, 4),
            "unit": "updates/sec",
            "vs_baseline": 1.0,
            "extra": {
                "static_check": _static_check_extra(),
                "num_clients": n,
                "store_backed": True,
                "store_build_sec": round(build_sec, 2),
                "placement": "stream",
                "sampler": "streaming",
                "population": True,
                "churn": True,
                "churn_trace": True,
                "async_versions": versions,
                "hier_edges": edges,
                "edge_dropout_rate": 0.1,
                "core_aggregator": "reputation",
                "platform": jax.devices()[0].platform,
                "timed_rounds": timed,
                "rounds_per_sec": round(timed / dt, 4) if dt > 0 else 0.0,
                "updates_absorbed": int(absorbed),
                "staleness_bound": 2 * s_max,
                "max_realized_staleness": int(max_stale),
                "staleness_p50": p50,
                "staleness_p90": p90,
                # per-tier / per-version absorbed breakdown — the
                # ISSUE 16 acceptance readout (a starved version or a
                # dead edge reads ~0 in its bucket)
                "per_version_absorbed": {
                    str(v): int(c)
                    for v, c in enumerate(exp._per_version_absorbed[:versions])
                },
                "per_edge_absorbed": {
                    str(e): int(c) for e, c in enumerate(exp._edge_absorbed)
                },
                "version_readmitted": int(exp._version_readmitted),
                "final_train_loss": round(
                    float(fetched[-1].train_loss), 4
                ),
                "peak_host_rss_mb": _peak_host_rss_mb(),
                "coverage_pct": pop_totals.get("population_coverage_pct"),
                "gather_workers": pop_totals.get("store_gather_workers"),
                "store_gather_mbps": pop_totals.get("store_gather_mbps"),
                "budget_floor_updates_per_sec": floor,
                "budget_staleness_bound": bound,
                "meets_budget": meets,
                "lora": False,
                "cohort_layout": cfg.run.cohort_layout,
                "control_plane": cfg.run.control_plane,
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# LoRA × store-scale entries (ROADMAP item 3 acceptance): BERT-tiny
# transformer federation over the mmap client store at 10³ and 10⁶
# clients, adapter-only uploads (rank-2 attention LoRA ⇒ ~133× fewer
# upload bytes than the full-delta twin at this geometry — recorded as
# extra.wire_reduction_vs_full), streaming sampler + paged ledger +
# population tracking. The acceptance bar mirrors PR 9's: the
# 10⁶-client entry's peak_host_rss_mb must stay within 1.5× the
# 10³-client twin's in the same BENCH_r*.json.
_LORA_SCALE = {
    "bert_lora_1k": 1_000,
    "bert_lora_1m": 1_000_000,
}


def bench_store_scale(name: str):
    import shutil
    import tempfile

    import jax

    from colearn_federated_learning_tpu.config import get_named_config
    from colearn_federated_learning_tpu.data.store import (
        build_synthetic_store,
    )
    from colearn_federated_learning_tpu.server.round_driver import Experiment

    n = _STORE_SCALE[name]
    warmup, timed = 2, 6
    tmp = tempfile.mkdtemp(prefix=f"bench_{name}_")
    try:
        t_build0 = time.perf_counter()
        build_synthetic_store(
            tmp, num_clients=n, examples_per_client=2, shape=(12, 12, 1),
            num_classes=10, seed=0, test_examples=64,
        )
        build_sec = time.perf_counter() - t_build0
        cfg = get_named_config("mnist_fedavg_2")
        cfg.apply_overrides({
            "data.num_clients": n, "data.store.dir": tmp,
            "data.placement": "stream", "server.sampling": "streaming",
            "server.cohort_size": 16, "client.batch_size": 2,
            "server.num_rounds": warmup + timed, "server.eval_every": 0,
            "server.checkpoint_every": 0, "run.out_dir": "",
            # the 1M-scale data-plane baseline (run.obs.population):
            # population tracking + the paged ledger feeding the
            # streaming sampler's sketch, so these entries record
            # coverage % and pager hit rate next to rounds/sec — the
            # numbers the federation health observatory gets judged by
            "run.obs.population.enabled": True,
            "run.obs.client_ledger.enabled": True,
            "run.obs.client_ledger.log_every": 2,
            "run.obs.client_ledger.hot_capacity": 64,
        })
        cfg.validate()
        exp = Experiment(cfg, echo=False)
        state = exp._place_state(exp.init_state())
        for r in range(warmup):
            state = exp.run_round(state, r)
            # the fit loop's per-round rebind: the ledger input is
            # donated, so snapshot refreshes must read the new array
            exp._ledger_ref = state.get("ledger")
            state.pop("_metrics")
        t0 = time.perf_counter()
        pending = []
        for r in range(warmup, warmup + timed):
            state = exp.run_round(state, r)
            exp._ledger_ref = state.get("ledger")
            pending.append(state.pop("_metrics"))
        fetched = jax.device_get(pending)
        dt = time.perf_counter() - t0
        rss = _peak_host_rss_mb()
        # end-of-run data-plane readout off the live tracker (the same
        # totals a full fit() would land in run_summary)
        pop_totals = exp._population.summary_totals(
            exp._pager, (exp.fed.train_x, exp.fed.train_y)
        )
        return {
            "metric": (
                f"FL rounds/sec ({n}-client mmap store, lenet5, "
                f"cohort {cfg.server.cohort_size}, streaming sampler)"
            ),
            "value": round(timed / dt, 4),
            "unit": "rounds/sec",
            "vs_baseline": 1.0,
            "extra": {
                "static_check": _static_check_extra(),
                "num_clients": n,
                "peak_host_rss_mb": rss,
                "store_backed": True,
                "store_build_sec": round(build_sec, 2),
                "placement": "stream",
                "sampler": "streaming",
                "platform": jax.devices()[0].platform,
                "timed_rounds": timed,
                "final_train_loss": round(
                    float(fetched[-1].train_loss), 4
                ),
                # the acceptance readout: compare this config's
                # peak_host_rss_mb against store_scale_1k's in the same
                # BENCH_r*.json — flat (≤1.5×) across the 1000× scale
                # step is ROADMAP item 1's bar
                "rss_budget_vs_1k": 1.5,
                # 1M-scale data-plane baseline (run.obs.population):
                # how much of the federation the timed run touched and
                # how the paged ledger's hot set behaved at this scale
                "population": True,
                "coverage_pct": pop_totals.get("population_coverage_pct"),
                "unique_clients_est": pop_totals.get(
                    "population_unique_clients"
                ),
                "pager_hit_rate": pop_totals.get("pager_hit_rate"),
                # store data plane (PR 19): resolved pool width + wall
                # gather throughput — BENCH_BUDGETS gates the floor
                "gather_workers": pop_totals.get("store_gather_workers"),
                "store_gather_mbps": pop_totals.get("store_gather_mbps"),
                "lora": False,
                "cohort_layout": cfg.run.cohort_layout,
                "control_plane": cfg.run.control_plane,
                "wire_reduction_vs_full": round(
                    exp.wire_reduction_vs_full(), 2
                ),
                "churn": bool(cfg.run.churn.enabled),
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_lora_scale(name: str):
    """The transformer twin of :func:`bench_store_scale`: a BERT-tiny
    LoRA federation over an on-the-fly synthetic LM store — adapter
    uploads, stream placement, streaming sampler fed by the paged
    ledger, population tracking. Records rounds/sec plus the three
    numbers the ROADMAP item-3 acceptance reads: peak_host_rss_mb
    (≤1.5× the 1k twin at 10⁶ clients), coverage_pct, and
    wire_reduction_vs_full."""
    import shutil
    import tempfile

    import jax

    from colearn_federated_learning_tpu.config import get_named_config
    from colearn_federated_learning_tpu.data.store import (
        build_synthetic_lm_store,
    )
    from colearn_federated_learning_tpu.server.round_driver import Experiment

    n = _LORA_SCALE[name]
    warmup, timed = 2, 6
    seq_len, vocab = 32, 64
    tmp = tempfile.mkdtemp(prefix=f"bench_{name}_")
    try:
        t_build0 = time.perf_counter()
        build_synthetic_lm_store(
            tmp, num_clients=n, examples_per_client=2, seq_len=seq_len,
            vocab_size=vocab, seed=0, test_examples=64,
        )
        build_sec = time.perf_counter() - t_build0
        cfg = get_named_config("bert_lora_federated")
        cfg.apply_overrides({
            "data.num_clients": n, "data.store.dir": tmp,
            "data.placement": "stream",
            "model.kwargs.seq_len": seq_len,
            "model.kwargs.vocab_size": vocab,
            "server.cohort_size": 16, "client.batch_size": 2,
            "server.num_rounds": warmup + timed, "server.eval_every": 0,
            "server.checkpoint_every": 0, "run.out_dir": "",
            "run.client_vmap_width": 1,
            "run.obs.population.enabled": True,
            "run.obs.client_ledger.enabled": True,
            "run.obs.client_ledger.log_every": 2,
            "run.obs.client_ledger.hot_capacity": 64,
        })
        cfg.validate()
        exp = Experiment(cfg, echo=False)
        state = exp._place_state(exp.init_state())
        for r in range(warmup):
            state = exp.run_round(state, r)
            exp._ledger_ref = state.get("ledger")
            state.pop("_metrics")
        t0 = time.perf_counter()
        pending = []
        for r in range(warmup, warmup + timed):
            state = exp.run_round(state, r)
            exp._ledger_ref = state.get("ledger")
            pending.append(state.pop("_metrics"))
        fetched = jax.device_get(pending)
        dt = time.perf_counter() - t0
        rss = _peak_host_rss_mb()
        pop_totals = exp._population.summary_totals(
            exp._pager, (exp.fed.train_x, exp.fed.train_y)
        )
        return {
            "metric": (
                f"FL rounds/sec ({n}-client mmap LM store, bert_tiny "
                f"rank-{cfg.model.lora.rank} LoRA, cohort "
                f"{cfg.server.cohort_size}, streaming sampler)"
            ),
            "value": round(timed / dt, 4),
            "unit": "rounds/sec",
            "vs_baseline": 1.0,
            "extra": {
                "static_check": _static_check_extra(),
                "num_clients": n,
                "peak_host_rss_mb": rss,
                "store_backed": True,
                "store_build_sec": round(build_sec, 2),
                "placement": "stream",
                "sampler": "streaming",
                "platform": jax.devices()[0].platform,
                "timed_rounds": timed,
                "final_train_loss": round(
                    float(fetched[-1].train_loss), 4
                ),
                # the PR 9 budget the acceptance reads: the 1m entry's
                # peak RSS vs the 1k twin's in the same BENCH_r*.json
                "rss_budget_vs_1k": 1.5,
                "population": True,
                "coverage_pct": pop_totals.get("population_coverage_pct"),
                "unique_clients_est": pop_totals.get(
                    "population_unique_clients"
                ),
                "pager_hit_rate": pop_totals.get("pager_hit_rate"),
                "gather_workers": pop_totals.get("store_gather_workers"),
                "store_gather_mbps": pop_totals.get("store_gather_mbps"),
                # the adapter-plane headline: full-delta ÷ adapter
                # upload bytes at this geometry (analytic, config-pure)
                "lora": True,
                "lora_rank": cfg.model.lora.rank,
                "lora_target": cfg.model.lora.target,
                "cohort_layout": cfg.run.cohort_layout,
                "control_plane": cfg.run.control_plane,
                "wire_reduction_vs_full": round(
                    exp.wire_reduction_vs_full(), 2
                ),
                "churn": bool(cfg.run.churn.enabled),
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="cifar10_fedavg_100",
                    choices=(sorted(_SHAPES) + sorted(_STORE_SCALE)
                             + sorted(_LORA_SCALE) + sorted(_WEAK_SCALE)
                             + sorted(_ASYNC_SCALE)
                             + sorted(_HIER_ASYNC_SCALE)))
    ap.add_argument("--matrix", action="store_true",
                    help="bench every config; one JSON line each")
    args = ap.parse_args(argv)
    if not args.matrix:
        if args.config in _WEAK_SCALE:
            print(json.dumps(bench_weak_scale(args.config)), flush=True)
        elif args.config in _LORA_SCALE:
            print(json.dumps(bench_lora_scale(args.config)), flush=True)
        elif args.config in _STORE_SCALE:
            print(json.dumps(bench_store_scale(args.config)), flush=True)
        elif args.config in _ASYNC_SCALE:
            print(json.dumps(bench_async_throughput(args.config)), flush=True)
        elif args.config in _HIER_ASYNC_SCALE:
            print(json.dumps(bench_hier_async(args.config)), flush=True)
        else:
            print(json.dumps(bench_config(args.config)), flush=True)
        return
    # Matrix mode re-execs one subprocess per config: each gets a clean
    # process (allocator stats aren't cumulative across configs, no
    # cross-config executable-cache contamination of HBM numbers).
    import subprocess
    import sys

    for name in (sorted(_SHAPES) + sorted(_STORE_SCALE)
                 + sorted(_LORA_SCALE) + sorted(_WEAK_SCALE)
                 + sorted(_ASYNC_SCALE) + sorted(_HIER_ASYNC_SCALE)):
        proc = subprocess.run(
            [sys.executable, __file__, "--config", name],
            capture_output=True, text=True,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        if proc.returncode != 0 or not line.startswith("{"):
            record = {"config": name, "error": proc.stderr[-500:]}
        else:
            record = dict(json.loads(line), config=name)
        print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
