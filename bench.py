"""Headline benchmark (BASELINE.json:2): FL rounds/sec and
client-updates/sec/chip on the 100-client CIFAR-10 ResNet-18 config.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` is relative to OUR first recorded TPU measurement in
BASELINE.md (the reference publishes no numbers — BASELINE.json:13
``"published": {}`` — so our own first light-up is the baseline the
driver tracks improvement against).
"""

from __future__ import annotations

import json
import time

# First recorded rounds/sec on 1× TPU v5 lite (see BASELINE.md measurements
# table): 2026-07-29, commit of milestone S0-S2. Later entries in that table
# track improvements against this number (bench reports vs_baseline).
BASELINE_ROUNDS_PER_SEC = 2.22

WARMUP_ROUNDS = 2
TIMED_ROUNDS = 8


def main():
    import jax

    from colearn_federated_learning_tpu.config import get_named_config
    from colearn_federated_learning_tpu.server.round_driver import Experiment

    cfg = get_named_config("cifar10_fedavg_100")
    cfg.server.num_rounds = WARMUP_ROUNDS + TIMED_ROUNDS
    cfg.server.eval_every = 0
    cfg.server.checkpoint_every = 0
    cfg.run.out_dir = ""
    # synthetic CIFAR-sized corpus (real CIFAR absent in this sandbox: zero
    # egress). Same shapes/cardinality as the real thing: 50k train examples.
    cfg.data.synthetic_train_size = 50_000
    cfg.data.synthetic_test_size = 1_000

    exp = Experiment(cfg, echo=False)
    state = exp.init_state()
    state = exp._place_state(state)

    # Rounds are dispatched asynchronously (the driver's production mode:
    # run.metrics_flush_every batches metric fetches); the timed region
    # ends with ONE metrics drain, which forces execution of every round
    # (each depends on the previous round's params). block_until_ready
    # alone does not sync through the axon remote-execution relay.
    for r in range(WARMUP_ROUNDS):
        state = exp.run_round(state, r)
        last_loss = float(state.pop("_metrics").train_loss)

    t0 = time.perf_counter()
    pending = []
    for r in range(WARMUP_ROUNDS, WARMUP_ROUNDS + TIMED_ROUNDS):
        state = exp.run_round(state, r)
        pending.append(state.pop("_metrics"))
    fetched = jax.device_get(pending)
    last_loss = float(fetched[-1].train_loss)
    dt = time.perf_counter() - t0

    rounds_per_sec = TIMED_ROUNDS / dt
    updates_per_sec_per_chip = (
        TIMED_ROUNDS * cfg.server.cohort_size / dt / exp.n_chips
    )
    vs = rounds_per_sec / BASELINE_ROUNDS_PER_SEC if BASELINE_ROUNDS_PER_SEC else 1.0
    print(json.dumps({
        "metric": "FL rounds/sec (100-client CIFAR-10, ResNet-18, cohort 16)",
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(vs, 4),
        "extra": {
            "client_updates_per_sec_per_chip": round(updates_per_sec_per_chip, 4),
            "n_chips": exp.n_chips,
            "timed_rounds": TIMED_ROUNDS,
            "platform": jax.devices()[0].platform,
            "data_source": exp.fed.meta.get("source"),
            "final_train_loss": round(last_loss, 4),
        },
    }))


if __name__ == "__main__":
    main()
